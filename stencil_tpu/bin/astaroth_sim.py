"""astaroth-sim driver — Astaroth MHD proxy benchmark.

Parity target: reference bin/astaroth_sim.cu: radius-3 26-direction halos,
sin-wave init, 6-point averaging stencil, interior/exchange/exterior overlap
loop, 5 fixed iterations (astaroth_sim.cu:184,223-274).  The reference prints
progress to stderr only; we additionally emit one jacobi3d-style CSV row so
runs are comparable:

    astaroth,<methods>,ranks,devCount,x,y,z,min(s),trimean(s)
"""

from __future__ import annotations

import argparse
import sys
import time

import jax

from stencil_tpu.bin import _common
from stencil_tpu.core.radius import Radius
from stencil_tpu.models.astaroth import AstarothSim
from stencil_tpu.utils.statistics import Statistics


def main(argv=None) -> int:
    p = argparse.ArgumentParser("astaroth-sim")
    # cxxopts options (astaroth_sim.cu:89-110): x/y/z size, transport flags
    p.add_argument("--x", type=int, default=512)
    p.add_argument("--y", type=int, default=512)
    p.add_argument("--z", type=int, default=512)
    p.add_argument("--iters", type=int, default=5)  # astaroth_sim.cu:223 fixed 5
    p.add_argument("--quantities", type=int, default=1, help="exchanged fields (real Astaroth: 8)")
    p.add_argument("--remote", dest="staged", action="store_true")
    p.add_argument("--cuda-aware-mpi", dest="cuda_aware_mpi", action="store_true")
    p.add_argument("--colocated", dest="colo", action="store_true")
    p.add_argument("--peer-copy", dest="peer", action="store_true")
    p.add_argument("--kernel", action="store_true")
    p.add_argument("--no-overlap", action="store_true")
    p.add_argument("--trivial", action="store_true")
    p.add_argument(
        "--kernel-impl",
        choices=["pallas", "jnp"],
        default="pallas",
        help="pallas plane-streaming kernel (fast) or XLA slices",
    )
    p.add_argument(
        "--schedule",
        choices=["auto", "per-step", "wavefront"],
        default="auto",
        help="auto (default): exchange every m<=3 steps with an m-level "
        "temporal wavefront kernel when shards are even (same field values "
        "up to last-ulp fusion effects, ~1/m the traffic; ~2.6x at 512^3), "
        "per-step otherwise; per-step: reference exchange-cadence parity "
        "(one exchange per iteration, modeling Astaroth's real comm volume); "
        "wavefront: force the temporal schedule (error when not viable)",
    )
    _common.add_telemetry_flags(p)
    _common.add_tune_flags(p)
    _common.add_stream_overlap_flag(p)
    _common.add_stream_halo_flag(p)
    _common.add_exchange_route_flag(p)
    _common.add_kernel_axis_flags(p)
    _common.add_numerics_flag(p)
    _common.add_checkpoint_flags(p)
    args = p.parse_args(argv)
    _common.telemetry_begin(args)
    _common.tune_begin(args)
    try:
        # restore the process-global tune overrides whatever happens —
        # sequential in-process runs must not inherit --no-tune/--tune-cache
        return _run(args)
    finally:
        _common.tune_end(args)


def _run(args) -> int:
    num_subdoms = len(jax.devices())
    print(f"assuming {num_subdoms} subdomains", file=sys.stderr)
    x, y, z = _common.fit_to_mesh(args.x, args.y, args.z, Radius.constant(3))
    print(f"domain: {x},{y},{z}", file=sys.stderr)

    kernel_impl = args.kernel_impl
    if args.no_overlap and kernel_impl == "pallas":
        print("--no-overlap forces --kernel-impl jnp", file=sys.stderr)
        kernel_impl = "jnp"
    if args.tune and kernel_impl == "pallas" and args.schedule != "auto":
        # a forced schedule maps to a forced stream path, and plan_stream
        # only consults the tuned plan on the unconstrained auto path — a
        # search here would be device work nothing ever reads
        print(
            f"--tune has no effect with --schedule {args.schedule} "
            "(forced route; tuned plans apply to schedule=auto only)",
            file=sys.stderr,
        )
    if args.tune and kernel_impl == "pallas" and args.schedule == "auto":
        # tune the generic stream engine's plan for this workload on a
        # throwaway model (the trials never advance its state), then let the
        # real build below consult the now-warm cache.  The cache is checked
        # BEFORE the throwaway model realizes — tune_key works pre-realize,
        # so a warm-cache --tune run really does zero work here (no field
        # allocation, no exchange compile)
        from stencil_tpu import tune
        from stencil_tpu.tune import runners as tune_runners

        tuner_sim = AstarothSim(
            x, y, z, num_quantities=args.quantities,
            strategy=_common.parse_strategy(args), kernel_impl="pallas",
            interpret=jax.default_backend() == "cpu", schedule=args.schedule,
        )
        if tune.best_config(tuner_sim.dd.tune_key("stream")) is not None:
            print("tune[stream]: source=cache (warm; zero trials)", file=sys.stderr)
        else:
            tuner_sim.realize()
            report = tune_runners.autotune_stream(
                tuner_sim.dd, tuner_sim._kernel, x_radius=1, separable=True,
                interpret=jax.default_backend() == "cpu",
                mxu_kernel=tuner_sim._kernel_mxu,
            )
            _common.tune_report_stderr(report)
        del tuner_sim
    sim = AstarothSim(
        x,
        y,
        z,
        num_quantities=args.quantities,
        overlap=not args.no_overlap,
        strategy=_common.parse_strategy(args),
        kernel_impl=kernel_impl,
        interpret=jax.default_backend() == "cpu",
        schedule=args.schedule,
        stream_overlap=args.stream_overlap,
        stream_halo=args.stream_halo,
        exchange_route=(
            None if args.exchange_route == "auto" else args.exchange_route
        ),
        **_common.kernel_axis_kwargs(args),
    )
    _common.apply_numerics(args, sim.dd)
    sim.realize()

    iter_time = Statistics()

    def timed_iter():
        t0 = time.perf_counter()
        sim.step()
        sim.block_until_ready()
        iter_time.insert(time.perf_counter() - t0)
        print(f"iter {iter_time.count() - 1}: {iter_time.max():e}s", file=sys.stderr)

    sup = _common.supervisor_for(
        args, sim.dd, label="astaroth",
        run_state=lambda: {"model": "astaroth", "quantities": args.quantities},
        on_mesh_change=sim.rebuild_after_reshard,
    )
    rc = 0
    if sup is not None:
        # supervised: no separate warm-up dispatch (bitwise kill/resume
        # comparability — see bin/jacobi3d.py); first sample absorbs compile
        def advance(n):
            for _ in range(n):
                timed_iter()

        out = sup.run(
            args.iters, advance,
            start_step=None if args.resume else 0, chunk=1,
        )
        rc = out.exit_code
    else:
        sim.step()  # compile
        sim.block_until_ready()
        for it in range(args.iters):
            timed_iter()

    if jax.process_index() == 0 and iter_time.count() > 0:
        ranks, dev_count = _common.ranks_and_devcount()
        print(
            f"astaroth,{_common.method_str(args)},{ranks},{dev_count},"
            f"{x},{y},{z},{iter_time.min()},{iter_time.trimean()}"
        )
    _common.telemetry_end(args)
    return rc


if __name__ == "__main__":
    sys.exit(main())
