"""bench-alltoallv — all-pairs transfer bandwidth under traffic matrices.

Parity target: reference bin/bench_alltoallv.cu: raw ``cudaMemcpyPeerAsync``
all-pairs bandwidth under 5 traffic matrices — a real stencil matrix,
all-to-all 8 MiB, all-to-all 1 GiB, block-local 1 GiB, local 1 GiB + remote
100 M (bench_alltoallv.cu:139-187).  The TPU equivalent drives the same
matrices over single-edge ``lax.ppermute`` transfers (the ICI point-to-point
path).  For the stencil matrix it prints per-pair ``bw`` and ``time``
matrices (bench_alltoallv.cu:101-113); every matrix also reports the total
seconds for one full traversal.
"""

from __future__ import annotations

import argparse
import sys

import jax
import numpy as np
from jax.sharding import Mesh

from stencil_tpu.bin import _common


def measure_pairs(devices, comm: np.ndarray, n_iters: int):
    """Per-pair transfer times for a bytes matrix; returns (times, total)."""
    n = len(devices)
    mesh = Mesh(np.array(devices), ("d",))
    times = np.zeros_like(comm, dtype=float)
    total = 0.0
    for i in range(n):
        for j in range(n):
            if i == j or comm[i, j] == 0:
                continue
            dt = _common.measure_edge(mesh, n, i, j, int(comm[i, j]), n_iters)
            times[i, j] = dt
            total += dt
    return times, total


def stencil_matrix(n: int, face: int, edge: int, corner: int) -> np.ndarray:
    """A real halo-traffic matrix: 3D-decompose n devices, neighbor weights by
    direction class (the reference embeds a measured 6-GPU matrix,
    bench_alltoallv.cu:139-150; we generate the same structure for any n)."""
    from stencil_tpu.core.dim3 import Dim3
    from stencil_tpu.parallel.partition import RankPartition

    part = RankPartition(Dim3(64, 64, 64), n)
    dim = part.dim()
    comm = np.zeros((n, n))
    for a in range(n):
        ia = part.dimensionize(a)
        for b in range(n):
            if a == b:
                continue
            d = part.dimensionize(b) - ia
            # periodic wrap (partition.hpp:777-790)
            vals = []
            for ax in range(3):
                v = d[ax]
                if v != 0 and v == dim[ax] - 1:
                    v = -1
                if v != 0 and v == 1 - dim[ax]:
                    v = 1
                vals.append(v)
            d = Dim3(*vals)
            if d == Dim3(0, 0, 0) or d.any_gt(1) or d.any_lt(-1):
                continue
            nz = sum(1 for v in (d.x, d.y, d.z) if v != 0)
            comm[a, b] = {1: face, 2: edge, 3: corner}[nz]
    return comm


def main(argv=None) -> int:
    p = argparse.ArgumentParser("bench-alltoallv")
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--scale", type=float, default=1.0, help="scale all matrix sizes")
    _common.add_telemetry_flags(p)
    args = p.parse_args(argv)
    _common.telemetry_begin(args)

    devices = jax.devices()
    n = len(devices)
    MiB = int(1024 * 1024 * args.scale)
    GiB = int(1024 * 1024 * 1024 * args.scale)

    mesh = Mesh(np.array(devices), ("d",))

    # 1) stencil matrix with per-pair bw/time report
    comm = stencil_matrix(n, face=8 * MiB, edge=MiB, corner=MiB // 4)
    times, total = measure_pairs(devices, comm, args.iters)
    print("bw")
    for i in range(n):
        print(" ".join(f"{(comm[i, j] / times[i, j]) if times[i, j] else 0:.4e}" for j in range(n)))
    print("time")
    for i in range(n):
        print(" ".join(f"{times[i, j]:.4e}" for j in range(n)))
    print("stencil")
    print(f"{total:e}")
    # the number this driver exists to produce: all pairs IN FLIGHT TOGETHER
    # (the reference batch-starts every pair on its own stream and times the
    # contended traversal, bench_alltoallv.cu:139-168); the sequential total
    # above is the uncontended baseline
    print("stencil concurrent")
    print(f"{_common.measure_matrix_concurrent(mesh, comm, args.iters):e}")

    # 2-5) aggregate-only matrices (bench_alltoallv.cu:173-187)
    ones = np.ones((n, n)) - np.eye(n)
    local = np.zeros((n, n))
    half = max(n // 2, 1)
    local[:half, :half] = 1
    local[half:, half:] = 1
    np.fill_diagonal(local, 0)
    remote = (ones - local).clip(0)
    for name, m in [
        ("All-to-all 8MiB", ones * 8 * MiB),
        ("All-to-all 1GiB", ones * GiB / max(n - 1, 1)),
        ("Local 1GiB", local * GiB / max(half, 1)),
        ("Local 1GiB Remote 100M", local * GiB / max(half, 1) + remote * 100 * MiB // 8),
    ]:
        _, total = measure_pairs(devices, m, args.iters)
        print(name)
        print(f"{total:e}")
        print(f"{name} concurrent")
        print(f"{_common.measure_matrix_concurrent(mesh, m.astype(np.int64), args.iters):e}")
    _common.telemetry_end(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
