"""measure-buf-exchange — feedback controller equalizing per-pair copy times.

Parity target: reference bin/measure_buf_exchange.cu: find per-pair message
sizes that make every device<->device transfer take the same target time
(4 ms), by gradient descent on the sizes over 50 iterations
(measure_buf_exchange.cu:32,189-223).  The TPU equivalent adjusts per-pair
``lax.ppermute`` payload sizes.  Per iteration it prints the size matrix ``x``
(MiB), measured times ``y``, and the adjustment ``dx``
(measure_buf_exchange.cu:91-96,180-185,209-214), then the final sizes.
"""

from __future__ import annotations

import argparse
import sys

import jax
import numpy as np
from jax.sharding import Mesh

from stencil_tpu.bin._common import measure_edge, measure_matrix_concurrent

MiB = 1024 * 1024


def print_mat(label: str, m: np.ndarray, fmt) -> None:
    print(label)
    for i in range(m.shape[0]):
        print(" ".join(fmt(m[i, j]) for j in range(m.shape[1])))


def main(argv=None) -> int:
    p = argparse.ArgumentParser("measure-buf-exchange")
    p.add_argument("--target", type=float, default=4e-3, help="target seconds per pair")
    p.add_argument("--iters", type=int, default=50, help="controller iterations")
    p.add_argument("--sub-iters", type=int, default=3, help="timing reps per measurement")
    p.add_argument("--init-mib", type=float, default=1.0, help="initial size (MiB)")
    p.add_argument(
        "--max-mib", type=float, default=256.0,
        help="per-pair size cap (MiB): a fast edge (e.g. a self-edge on one "
        "chip, ~hundreds of GB/s) would otherwise need GB-scale buffers to "
        "reach the 4 ms target and exhaust HBM before converging",
    )
    p.add_argument("--tol", type=float, default=0.05, help="relative convergence tolerance")
    from stencil_tpu.bin import _common

    _common.add_telemetry_flags(p)
    args = p.parse_args(argv)
    _common.telemetry_begin(args)

    devices = jax.devices()
    n = len(devices)
    mesh = Mesh(np.array(devices), ("d",))

    x = np.zeros((n, n))  # per-pair sizes in bytes
    init_mib = min(args.init_mib, args.max_mib)  # the cap binds the init too
    for i in range(n):
        for j in range(n):
            if i != j or n == 1:
                x[i, j] = init_mib * MiB

    for it in range(args.iters):
        y = np.zeros((n, n))
        for i in range(n):
            for j in range(n):
                if x[i, j] == 0:
                    continue
                y[i, j] = measure_edge(mesh, n, i, j, int(x[i, j]), args.sub_iters)
        # multiplicative update toward the target time (the reference's
        # per-pair gradient step, measure_buf_exchange.cu:189-223)
        active = x > 0
        ratio = np.ones_like(x)
        ratio[active] = args.target / y[active]
        ratio = ratio.clip(0.5, 2.0)  # damp
        dx = (x * ratio - x).astype(np.int64)
        print_mat("x", x / MiB, lambda v: f"{v:.2f}")
        print_mat("y", y, lambda v: f"{v:.4e}")
        print_mat("dx", dx, lambda v: f"{int(v)}")
        # contended traversal at the current sizes: all pairs in flight in one
        # dispatch (the reference's latch-kernel batch start equalizes exactly
        # these concurrent copies, measure_buf_exchange.cu:120-159; TPU has no
        # per-collective event timers, so the per-pair y stays sequential and
        # the contention shows up in this total)
        print(
            f"y_concurrent {measure_matrix_concurrent(mesh, x.astype(np.int64), args.sub_iters):.4e}"
        )
        # a capped pair that is still UNDER the target cannot converge (the
        # size it needs is disallowed) — excuse it; an over-target pair can
        # always shrink, so it must still meet tolerance
        at_cap = (x >= args.max_mib * MiB) & (y < args.target)
        converged = np.all(
            (np.abs(y[active] - args.target) <= args.tol * args.target)
            | at_cap[active]
        )
        if converged:
            break
        x = (x + dx).clip(4096, args.max_mib * MiB) * active

    print("final x (MiB)")
    for i in range(n):
        print(" ".join(f"{x[i, j] / MiB:.2f}" for j in range(n)))
    _common.telemetry_end(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
