"""bench-exchange — microbenchmark sweep of radius shapes + route A/B.

Parity target: reference bin/bench_exchange.cu: on a global compute-domain
extent (default 128^3, bench_exchange.cu:21,84 — ``fit_to_mesh`` rescales it
to the mesh, so per-device extent SHRINKS as devices grow, exactly the
reference semantics), run exchange+swap under a sweep of radius
configurations — +x-only, ±x, faces-only, faces+edges(eR), uniform —
and report the reference's exact CSV (bench_exchange.cu:57-64):

    name,count,trimean (S),trimean (B/s),stddev,min,avg,max

Beyond the reference: ``--route`` pins the y/z-sweep exchange route
(ops/exchange.py ``EXCHANGE_ROUTES``) for the sweep, and a direct-vs-packed
A/B section measures every engageable route under the burst-aware protocol
(``tune.trial.measure_alternating``: alternate within one process, drop the
post-idle-burst rep 0, steady-state median) with a per-axis (x/y/z) ms
breakdown — so the ~64×-amplified thin-z claim (PERF_NOTES "Thin z-region
access") AND the ~8/(2r) sublane-amplified thin-y claim ("Thin y-region
access") are re-measurable per chip generation.  Legs a route does not
change (x always; y on the z-only packed routes) are measured once under
``direct`` and shared — ``shared_legs_with_direct`` records exactly which,
per route.  The section is emitted as one machine-readable JSON line on
stdout (the bench.py convention).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp

from stencil_tpu.bin import _common
from stencil_tpu.core.dim3 import Dim3
from stencil_tpu.core.radius import Radius
from stencil_tpu.domain import DistributedDomain
from stencil_tpu.utils.statistics import Statistics

#: sweep axes of the per-axis breakdown, exchange-axis index by name
_AXES = {"x": 0, "y": 1, "z": 2}


def bench(n_iters: int, n_quants: int, ext, radius: Radius, inner: int = 1,
          rt: float = 0.0, route: str = None):
    """One config: returns (Statistics of per-iter seconds, exchanged bytes
    per the 26-message model, swept wire bytes).

    ``inner > 1`` runs that many exchanges per device dispatch
    (``exchange_many``) and divides, with the measured host round trip ``rt``
    subtracted — the honest protocol for tunneled backends where a per-call
    sync costs ~100 ms (see bench.py).  ``route`` pins the z-sweep exchange
    route (None = planner resolution)."""
    x, y, z = _common.fit_to_mesh(ext[0], ext[1], ext[2], radius)
    dd = DistributedDomain(x, y, z)
    dd.set_radius(radius)
    if route is not None:
        dd.set_exchange_route(route)
    for i in range(n_quants):
        dd.add_data(f"d{i}", dtype=jnp.float32)
    dd.realize()
    stats = Statistics()
    from stencil_tpu.core.geometry import sweep_bytes

    swept = sweep_bytes(dd.local_spec(), [jnp.dtype(jnp.float32).itemsize] * n_quants) * dd.num_subdomains()
    if inner > 1:
        def run(k):
            dd.exchange_many(k)
            dd.block_until_ready()

        # auto-scaled so the rt subtraction can never clamp to 0.0
        samples, _ = _common.timed_inner_loop(run, inner, rt, n_iters)
        for s in samples:
            stats.insert(s)
        return stats, dd.exchange_bytes_total(), swept
    dd.exchange()  # compile
    dd.swap()
    dd.block_until_ready()
    for _ in range(n_iters):
        t0 = time.perf_counter()
        dd.exchange()
        dd.swap()
        dd.block_until_ready()
        stats.insert(time.perf_counter() - t0)
    return stats, dd.exchange_bytes_total(), swept


def report_header() -> str:
    # reference columns (bench_exchange.cu:57-64) + one honesty column: the
    # 3-axis sweeps send full-extent slabs, so actual wire bytes exceed the
    # 26-message model for sparse radii (core/geometry.py sweep_bytes)
    return "name,count,trimean (S),trimean (B/s),stddev,min,avg,max,trimean (B/s swept)"


def report(cfg: str, bytes_: int, stats: Statistics, swept: int = 0) -> str:
    tm = stats.trimean()
    bps = bytes_ / tm if tm else float("nan")
    sps = swept / tm if tm else float("nan")
    return (
        f"{cfg},{stats.count()},{tm:e},{bps:e},"
        f"{stats.stddev():e},{stats.min():e},{stats.avg():e},{stats.max():e},{sps:e}"
    )


def sweep_configs(ext, fR: int, eR: int):
    """The five radius shapes of bench_exchange.cu:121-195."""
    tag = f"{ext[0]}-{ext[1]}-{ext[2]}"

    r = Radius.constant(0)
    r.set_dir(Dim3(1, 0, 0), fR)
    yield f"{tag}/px/{fR}", r

    r = Radius.constant(0)
    r.set_dir(Dim3(1, 0, 0), fR)
    r.set_dir(Dim3(-1, 0, 0), fR)
    yield f"{tag}/x/{fR}", r

    r = Radius.constant(0)
    r.set_face(fR)
    yield f"{tag}/faces/{fR}", r

    r = Radius.constant(fR)
    for sx in (1, -1):
        for sy in (1, -1):
            for sz in (1, -1):
                r.set_dir(Dim3(sx, sy, sz), eR)
    yield f"{tag}/face&edge/{fR}/{eR}", r

    yield f"{tag}/uniform/2", Radius.constant(2)


def _route_measured_axes(route: str) -> list:
    """The per-axis legs a route must measure ITSELF: a leg may only be
    shared from ``direct`` when the route compiles a byte-identical program
    for that sweep.  The x sweep is identical on every route (nothing packs
    x-plane slabs); the y sweep differs on the ``yzpack_*`` routes (the
    packed sublane-major message) and the z sweep on every packed route."""
    from stencil_tpu.ops.exchange import Y_PACK_ROUTES

    if route == "direct":
        return ["x", "y", "z"]
    if route in Y_PACK_ROUTES:
        return ["y", "z"]
    return ["z"]


def route_ab(ext, fR: int, n_quants: int, reps: int, rt: float, inner: int = 4) -> dict:
    """Direct-vs-packed steady-state A/B at the uniform radius — every
    engageable route's full exchange plus its per-axis (x/y/z) sweeps, all
    alternating in ONE process under the trial protocol (rep-0 drop,
    steady-state median).  Returns the JSON section."""
    from jax import lax
    from functools import partial

    from stencil_tpu.ops.exchange import EXCHANGE_ROUTES, route_supported
    from stencil_tpu.tune.runners import _force_done
    from stencil_tpu.tune.trial import measure_alternating

    radius = Radius.constant(fR)
    x, y, z = _common.fit_to_mesh(ext[0], ext[1], ext[2], radius)
    dd = DistributedDomain(x, y, z)
    dd.set_radius(radius)
    for i in range(n_quants):
        dd.add_data(f"d{i}", dtype=jnp.float32)
    dd.realize()
    dtypes = [h.dtype for h in dd._handles]
    routes = [
        r
        for r in EXCHANGE_ROUTES
        if r == "direct" or route_supported(r, dtypes, dd._valid_last)
    ]
    packed_ok = len(routes) > 1

    def make_run(fn):
        @partial(jax.jit, static_argnums=1)
        def many(arrays, s):
            return lax.fori_loop(0, s, lambda _, a: fn(a), arrays)

        def run(n):
            out = many(dd._curr, n)
            _force_done(next(iter(out.values())))

        return run

    labels, runs = [], []
    for route in routes:
        labels.append((route, "all"))
        runs.append(make_run(dd.make_exchange_route_fn(route, donate=False)))
        # a route measures only the sweeps it CHANGES; the still-identical
        # legs (x always; y for the z-only packed routes) compile
        # byte-identical programs and are measured once under direct, then
        # shared into the breakdown below — with the shared legs recorded
        # per route in ``shared_legs_with_direct``
        for ax_name in _route_measured_axes(route):
            labels.append((route, ax_name))
            runs.append(
                make_run(
                    dd.make_exchange_route_fn(
                        route, donate=False, axes=(_AXES[ax_name],)
                    )
                )
            )
    # calibrate the dispatch size once on the first run (shared workload —
    # one inner count keeps rounds comparable), re-warm the rest at it
    _, inner = _common.timed_inner_loop(runs[0], inner, rt, 1)
    for run in runs[1:]:
        run(inner)
    rounds = measure_alternating(runs, inner, rt, reps)
    import statistics as _st

    section: dict = {
        "fit_extent": [x, y, z],
        "radius": fR,
        "quantities": n_quants,
        "packed_eligible": packed_ok,
        "measurement_protocol": {
            "alternating_within_process": True,
            "drop_rep0": True,
            "statistic": "median",
            "reps": reps,
            "inner": inner,
        },
        "routes": {},
    }
    for (route, part), samples in zip(labels, rounds):
        entry = section["routes"].setdefault(
            route, {"ms_per_exchange": None, "per_axis_ms": {}}
        )
        ms = _st.median(samples) * 1e3
        if part == "all":
            entry["ms_per_exchange"] = ms
        else:
            entry["per_axis_ms"][part] = ms
    # fill the unmeasured legs from direct's figures (identical programs)
    # and record WHICH legs were shared, per route — the provenance a
    # reader needs before trusting a leg that was never re-measured
    shared: dict = {}
    for route, entry in section["routes"].items():
        if route == "direct":
            continue
        shared[route] = [
            ax for ax in ("x", "y", "z") if ax not in entry["per_axis_ms"]
        ]
        for ax_name in shared[route]:
            entry["per_axis_ms"][ax_name] = section["routes"]["direct"][
                "per_axis_ms"
            ][ax_name]
    section["measurement_protocol"]["shared_legs_with_direct"] = shared
    direct = section["routes"]["direct"]["ms_per_exchange"]
    section["speedup_vs_direct"] = {
        route: (direct / e["ms_per_exchange"]) if e["ms_per_exchange"] else None
        for route, e in section["routes"].items()
        if route != "direct"
    }
    return section


def main(argv=None) -> int:
    p = argparse.ArgumentParser("bench-exchange")
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--quantities", type=int, default=1)
    p.add_argument("--x", type=int, default=128)
    p.add_argument("--y", type=int, default=128)
    p.add_argument("--z", type=int, default=128)
    p.add_argument("--face-radius", type=int, default=2, dest="fR")
    p.add_argument("--edge-radius", type=int, default=1, dest="eR")
    from stencil_tpu.ops.exchange import EXCHANGE_ROUTES

    p.add_argument(
        "--route",
        default="auto",
        choices=("auto",) + EXCHANGE_ROUTES,
        help="y/z-sweep exchange route for the CSV sweep (auto = planner "
        "resolution: env > tuned config > direct; see docs/tuning.md "
        "'Exchange routes')",
    )
    p.add_argument(
        "--ab-reps",
        type=int,
        default=3,
        metavar="N",
        help="steady-state reps for the direct-vs-packed route A/B section "
        "(alternating protocol, rep 0 dropped; 0 skips the section)",
    )
    p.add_argument(
        "--inner",
        type=int,
        default=None,
        help="exchanges per device dispatch (use >1 on tunneled backends; "
        "per-iter time = (dispatch - host_rt) / inner; default: 1, or "
        "auto-raised when the host round trip would swamp the exchange)",
    )
    _common.add_telemetry_flags(p)
    args = p.parse_args(argv)
    _common.telemetry_begin(args)

    rt = _common.host_round_trip_s()
    if args.inner is None:
        args.inner = 1
        if rt > 10e-3:
            # unset --inner + a tunnel-scale round trip (~100 ms; a real
            # host is ~us): a per-iteration sync would swamp the exchange,
            # so switch to the exchanges-per-dispatch protocol
            args.inner = 16
            if jax.process_index() == 0:
                print(
                    f"host round trip {rt*1e3:.0f} ms: auto --inner 16 "
                    "(per-iter time = (dispatch - rt) / inner)",
                    file=sys.stderr,
                )
    if args.inner == 1:
        rt = 0.0
    ext = (args.x, args.y, args.z)
    route = None if args.route == "auto" else args.route
    if jax.process_index() == 0:
        print(report_header())
    for name, radius in sweep_configs(ext, args.fR, args.eR):
        stats, bytes_, swept = bench(
            args.iters, args.quantities, ext, radius, args.inner, rt, route
        )
        if jax.process_index() == 0:
            print(report(name, bytes_, stats, swept))
    result = {
        "bench": "exchange",
        "extent": list(ext),
        "quantities": args.quantities,
        "route_flag": args.route,
        "host_round_trip_s": rt,
    }
    if args.ab_reps > 0:
        ab_rt = rt if args.inner > 1 else 0.0
        result["route_ab"] = route_ab(
            ext, args.fR, args.quantities, args.ab_reps, ab_rt
        )
    if jax.process_index() == 0:
        print(json.dumps(result))
    _common.telemetry_end(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
