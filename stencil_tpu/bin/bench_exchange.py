"""bench-exchange — microbenchmark sweep of radius shapes.

Parity target: reference bin/bench_exchange.cu: on a global compute-domain
extent (default 128^3, bench_exchange.cu:21,84 — ``fit_to_mesh`` rescales it
to the mesh, so per-device extent SHRINKS as devices grow, exactly the
reference semantics), run exchange+swap under a sweep of radius
configurations — +x-only, ±x, faces-only, faces+edges(eR), uniform —
and report the reference's exact CSV (bench_exchange.cu:57-64):

    name,count,trimean (S),trimean (B/s),stddev,min,avg,max
"""

from __future__ import annotations

import argparse
import sys
import time

import jax
import jax.numpy as jnp

from stencil_tpu.bin import _common
from stencil_tpu.core.dim3 import Dim3
from stencil_tpu.core.radius import Radius
from stencil_tpu.domain import DistributedDomain
from stencil_tpu.utils.statistics import Statistics


def bench(n_iters: int, n_quants: int, ext, radius: Radius, inner: int = 1, rt: float = 0.0):
    """One config: returns (Statistics of per-iter seconds, exchanged bytes).

    ``inner > 1`` runs that many exchanges per device dispatch
    (``exchange_many``) and divides, with the measured host round trip ``rt``
    subtracted — the honest protocol for tunneled backends where a per-call
    sync costs ~100 ms (see bench.py)."""
    x, y, z = _common.fit_to_mesh(ext[0], ext[1], ext[2], radius)
    dd = DistributedDomain(x, y, z)
    dd.set_radius(radius)
    for i in range(n_quants):
        dd.add_data(f"d{i}", dtype=jnp.float32)
    dd.realize()
    stats = Statistics()
    from stencil_tpu.core.geometry import sweep_bytes

    swept = sweep_bytes(dd.local_spec(), [jnp.dtype(jnp.float32).itemsize] * n_quants) * dd.num_subdomains()
    if inner > 1:
        def run(k):
            dd.exchange_many(k)
            dd.block_until_ready()

        # auto-scaled so the rt subtraction can never clamp to 0.0
        samples, _ = _common.timed_inner_loop(run, inner, rt, n_iters)
        for s in samples:
            stats.insert(s)
        return stats, dd.exchange_bytes_total(), swept
    dd.exchange()  # compile
    dd.swap()
    dd.block_until_ready()
    for _ in range(n_iters):
        t0 = time.perf_counter()
        dd.exchange()
        dd.swap()
        dd.block_until_ready()
        stats.insert(time.perf_counter() - t0)
    return stats, dd.exchange_bytes_total(), swept


def report_header() -> str:
    # reference columns (bench_exchange.cu:57-64) + one honesty column: the
    # 3-axis sweeps send full-extent slabs, so actual wire bytes exceed the
    # 26-message model for sparse radii (core/geometry.py sweep_bytes)
    return "name,count,trimean (S),trimean (B/s),stddev,min,avg,max,trimean (B/s swept)"


def report(cfg: str, bytes_: int, stats: Statistics, swept: int = 0) -> str:
    tm = stats.trimean()
    bps = bytes_ / tm if tm else float("nan")
    sps = swept / tm if tm else float("nan")
    return (
        f"{cfg},{stats.count()},{tm:e},{bps:e},"
        f"{stats.stddev():e},{stats.min():e},{stats.avg():e},{stats.max():e},{sps:e}"
    )


def sweep_configs(ext, fR: int, eR: int):
    """The five radius shapes of bench_exchange.cu:121-195."""
    tag = f"{ext[0]}-{ext[1]}-{ext[2]}"

    r = Radius.constant(0)
    r.set_dir(Dim3(1, 0, 0), fR)
    yield f"{tag}/px/{fR}", r

    r = Radius.constant(0)
    r.set_dir(Dim3(1, 0, 0), fR)
    r.set_dir(Dim3(-1, 0, 0), fR)
    yield f"{tag}/x/{fR}", r

    r = Radius.constant(0)
    r.set_face(fR)
    yield f"{tag}/faces/{fR}", r

    r = Radius.constant(fR)
    for sx in (1, -1):
        for sy in (1, -1):
            for sz in (1, -1):
                r.set_dir(Dim3(sx, sy, sz), eR)
    yield f"{tag}/face&edge/{fR}/{eR}", r

    yield f"{tag}/uniform/2", Radius.constant(2)


def main(argv=None) -> int:
    p = argparse.ArgumentParser("bench-exchange")
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--quantities", type=int, default=1)
    p.add_argument("--x", type=int, default=128)
    p.add_argument("--y", type=int, default=128)
    p.add_argument("--z", type=int, default=128)
    p.add_argument("--face-radius", type=int, default=2, dest="fR")
    p.add_argument("--edge-radius", type=int, default=1, dest="eR")
    p.add_argument(
        "--inner",
        type=int,
        default=None,
        help="exchanges per device dispatch (use >1 on tunneled backends; "
        "per-iter time = (dispatch - host_rt) / inner; default: 1, or "
        "auto-raised when the host round trip would swamp the exchange)",
    )
    _common.add_telemetry_flags(p)
    args = p.parse_args(argv)
    _common.telemetry_begin(args)

    rt = _common.host_round_trip_s()
    if args.inner is None:
        args.inner = 1
        if rt > 10e-3:
            # unset --inner + a tunnel-scale round trip (~100 ms; a real
            # host is ~us): a per-iteration sync would swamp the exchange,
            # so switch to the exchanges-per-dispatch protocol
            args.inner = 16
            if jax.process_index() == 0:
                print(
                    f"host round trip {rt*1e3:.0f} ms: auto --inner 16 "
                    "(per-iter time = (dispatch - rt) / inner)",
                    file=sys.stderr,
                )
    if args.inner == 1:
        rt = 0.0
    ext = (args.x, args.y, args.z)
    if jax.process_index() == 0:
        print(report_header())
    for name, radius in sweep_configs(ext, args.fR, args.eR):
        stats, bytes_, swept = bench(args.iters, args.quantities, ext, radius, args.inner, rt)
        if jax.process_index() == 0:
            print(report(name, bytes_, stats, swept))
    _common.telemetry_end(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
