"""pingpong — point-to-point latency/bandwidth sweep.

Parity target: reference bin/pingpong.cu: MPI host-buffer pingpong between
node pairs for sizes 2^min..2^max bytes (pingpong.cu:56-99).  The TPU-native
equivalent measures a chip<->chip round trip: a paired ``lax.ppermute``
(dev0 -> dev1 -> dev0) over the device mesh — the fabric the halo exchange
rides — for the same size sweep.  With one device the permute wraps to self
(the intra-chip copy path).  Output: one row per device pair,
one column per size:

    <src>-<dst> <t(2^min)> <t(2^min+1)> ...
"""

from __future__ import annotations

import argparse
import sys
import time

import jax

from stencil_tpu.bin import _common
from stencil_tpu.utils.compat import shard_map
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def pingpong_times(devices, min_n: int, max_n: int, n_iters: int):
    """For each adjacent device pair, time a there-and-back single-edge
    ppermute (src -> dst -> src) per message size."""
    n_dev = len(devices)
    mesh = Mesh(np.array(devices), ("d",))

    def make_rt(src: int, dst: int, n_elems: int):
        sharding = NamedSharding(mesh, P("d"))

        @jax.jit
        def rt(x):
            def f(blk):
                fwd = lax.ppermute(blk, "d", [(src, dst)])
                return lax.ppermute(fwd, "d", [(dst, src)])

            return shard_map(f, mesh=mesh, in_specs=P("d"), out_specs=P("d"))(x)

        x = jax.device_put(jnp.zeros((n_elems * n_dev,), jnp.float32), sharding)
        return rt, x

    rows = []
    for pair in range(max(n_dev - 1, 1)):
        src, dst = pair, (pair + 1) % n_dev
        times = []
        for p in range(min_n, max_n + 1):
            nbytes = 1 << p
            rt, x = make_rt(src, dst, max(nbytes // 4, 1))
            rt(x).block_until_ready()  # compile
            t0 = time.perf_counter()
            for _ in range(n_iters):
                x = rt(x)
            x.block_until_ready()
            times.append((time.perf_counter() - t0) / n_iters)
        rows.append((f"{devices[src].id}-{devices[dst].id}", times))
    return rows


def main(argv=None) -> int:
    p = argparse.ArgumentParser("pingpong")
    p.add_argument("ranks_per_node", type=int, nargs="?", default=1)
    p.add_argument("--min", type=int, default=0, help="log2 of smallest message")
    p.add_argument("--max", type=int, default=27, help="log2 of largest message")
    p.add_argument("--iters", type=int, default=30)
    _common.add_telemetry_flags(p)
    args = p.parse_args(argv)
    _common.telemetry_begin(args)

    rows = pingpong_times(jax.devices(), args.min, args.max, args.iters)
    for name, times in rows:
        print(name + " " + " ".join(f"{t:e}" for t in times))
    _common.telemetry_end(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
