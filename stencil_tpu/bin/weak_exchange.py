"""weak-exchange — weak.cu variant timing the whole loop with one wall clock.

Parity target: reference bin/weak_exchange.cu (one elapsed wall time over all
iterations instead of per-phase stats; weak_exchange.cu:125-179).  Row layout
matches weak.cu's bytes columns with a single trailing elapsed-seconds field:

    weak,<methods>,x,y,z,s,MPI(B),Colocated(B),cudaMemcpyPeer(B),direct(B),
    iters,gpus,nodes,ranks,elapsed
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp

from stencil_tpu.bin import _common
from stencil_tpu.bin.weak import build_parser
from stencil_tpu.core.radius import Radius
from stencil_tpu.domain import DistributedDomain
from stencil_tpu.models.jacobi import weak_scaled_size
from stencil_tpu.utils.config import MethodFlags


def main(argv=None) -> int:
    args = build_parser("weak-exchange", overlap_flags=False).parse_args(argv)
    args.trivial = args.naive
    _common.telemetry_begin(args)
    devs = len(jax.devices())
    x = weak_scaled_size(args.x, devs)
    y = weak_scaled_size(args.y, devs)
    z = weak_scaled_size(args.z, devs)
    x, y, z = _common.fit_to_mesh(x, y, z, Radius.constant(3))

    dd = DistributedDomain(x, y, z)
    dd.set_methods(_common.parse_methods(args))
    dd.set_radius(Radius.constant(3))
    dd.set_placement(_common.parse_strategy(args))
    _common.apply_exchange_route(args, dd)
    for i in range(4):
        dd.add_data(f"d{i}", dtype=jnp.float32)
    dd.realize()

    # one warm call so jit compilation stays out of the wall clock
    dd.exchange()
    dd.swap()
    dd.block_until_ready()

    t0 = time.perf_counter()
    for _ in range(args.n_iters):
        dd.exchange()
        dd.swap()
    dd.block_until_ready()
    elapsed = time.perf_counter() - t0

    if jax.process_index() == 0:
        ranks, dev_count = _common.ranks_and_devcount()
        print(
            f"weak,{_common.method_str(args)},{x},{y},{z},{x * y * z},"
            f"{dd.exchange_bytes_for_method(MethodFlags.CudaMpi)},0,0,0,"
            f"{args.n_iters},{ranks * dev_count},{ranks},{ranks},{elapsed:e}"
        )
    _common.telemetry_end(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
