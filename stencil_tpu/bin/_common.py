"""Shared driver plumbing: method flags, timing loops, CSV emission."""

from __future__ import annotations

import argparse
import os
import time

import jax

from stencil_tpu.utils.compat import shard_map

from stencil_tpu.utils.config import MethodFlags, PlacementStrategy


def add_method_flags(p: argparse.ArgumentParser) -> None:
    """The reference's transport-selection flags (jacobi3d.cu:111-120).  All
    map onto the collective exchange on TPU; they are accepted (and echoed in
    the CSV method string) so reference run scripts keep working."""
    p.add_argument("--staged", action="store_true", help="Enable RemoteSender/Recver (ppermute on TPU)")
    p.add_argument("--cuda-aware-mpi", action="store_true", help="Enable CudaAwareMpiSender/Recver (ppermute)")
    p.add_argument("--colo", action="store_true", help="Enable ColocatedHaloSender/Recver (ppermute)")
    p.add_argument("--peer", action="store_true", help="Enable PeerAccessSender (ppermute)")
    p.add_argument("--kernel", action="store_true", help="Enable PeerCopySender (ppermute)")
    p.add_argument("--trivial", action="store_true", help="Skip node-aware placement")


def parse_methods(args) -> MethodFlags:
    m = MethodFlags.Non
    if args.staged:
        m |= MethodFlags.CudaMpi
    if getattr(args, "cuda_aware_mpi", False):
        m |= MethodFlags.CudaAwareMpi
    if args.colo:
        m |= MethodFlags.CudaMpiColocated
    if args.peer:
        m |= MethodFlags.CudaMemcpyPeer
    if args.kernel:
        m |= MethodFlags.CudaKernel
    if m == MethodFlags.Non:
        m = MethodFlags.All
    return m


def method_str(args) -> str:
    """jacobi3d.cu:355-374 method string."""
    parts = []
    if args.staged:
        parts.append("staged")
    if getattr(args, "cuda_aware_mpi", False):
        parts.append("cuda-aware")
    if args.colo:
        parts.append("colo")
    if args.peer:
        parts.append("peer")
    if args.kernel:
        parts.append("kernel")
    if not parts:
        parts.append("ppermute")  # TPU default method
    return "/".join(parts)


def parse_strategy(args) -> PlacementStrategy:
    return PlacementStrategy.Trivial if args.trivial else PlacementStrategy.NodeAware


def add_telemetry_flags(p: argparse.ArgumentParser) -> None:
    """Every driver grows ``--metrics-out``: write the telemetry snapshot
    (counters/gauges/histogram stats, JSON) to PATH at exit.  Passing it
    turns telemetry on for the run; with ``STENCIL_TELEMETRY_DIR`` also set,
    the run additionally leaves a JSONL event log and a Chrome trace there."""
    p.add_argument(
        "--metrics-out",
        default=None,
        metavar="PATH",
        help="write a telemetry snapshot JSON to PATH at exit (enables "
        "telemetry; see docs/observability.md)",
    )


def add_profile_flags(p: argparse.ArgumentParser) -> None:
    """``--profile-dir``: cadence-gated ``jax.profiler`` captures around the
    driver's dispatches (``STENCIL_PROFILE_EVERY`` sets the cadence; unset
    = one capture).  At exit the device rows are merged into the Chrome
    trace and a per-phase roofline report lands next to the captures —
    docs/observability.md "Device-time attribution".  Degrades to a warning
    on backends with no profiler."""
    p.add_argument(
        "--profile-dir",
        default=None,
        metavar="DIR",
        help="capture jax.profiler traces here on the STENCIL_PROFILE_EVERY "
        "cadence; device rows are merged into the Chrome trace and a "
        "roofline report is written at exit (see docs/observability.md)",
    )


def profile_capture_for(args):
    """A configured ``ProfileCapture`` from ``add_profile_flags``'s choice
    (environment fills an unset flag), or None — profiling is opt-in."""
    from stencil_tpu.telemetry.device import ProfileCapture

    return ProfileCapture.from_env(dir=getattr(args, "profile_dir", None))


def profile_finalize(args, capture, chrome_path: str = None) -> None:
    """End-of-run device-truth artifacts: merge the newest capture's device
    rows into the host Chrome trace at ``chrome_path`` (one Perfetto
    timeline) and write the per-phase roofline report into the profile
    dir.  Runs AFTER the final host-trace dump (``telemetry_end`` orders
    this) so nothing re-dumps over the merged rows.  Best-effort — a
    missing trace (no profiler backend) degrades to nothing, never an
    error on the driver's exit path."""
    if capture is None or capture.captures == 0:
        return
    import sys

    from stencil_tpu.telemetry.device import merge_into_chrome_trace
    from stencil_tpu.telemetry.roofline import capture_report, render_markdown
    from stencil_tpu.utils.artifact import atomic_write_json, atomic_write_text

    try:
        if chrome_path is not None:
            merge_into_chrome_trace(chrome_path, capture.dir)
        from stencil_tpu.tune.key import chip_kind

        report = capture_report(capture, chip=chip_kind())
        if report is None:
            print(
                f"profile: no device rows under {capture.dir} (backend "
                "without a device profiler?) — no roofline report; "
                "scripts/perf_report.py can build a host-span fallback",
                file=sys.stderr,
            )
            return
        atomic_write_json(os.path.join(capture.dir, "roofline.json"), report)
        atomic_write_text(
            os.path.join(capture.dir, "roofline.md"), render_markdown(report)
        )
        print(f"profile: roofline report in {capture.dir}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — observability must not fail the run
        print(f"profile finalize failed: {e!r}", file=sys.stderr)


def add_tune_flags(p: argparse.ArgumentParser) -> None:
    """Autotuner knobs shared by the model drivers (docs/tuning.md):
    ``--tune`` runs the on-device search for this driver's workload before
    the model builds (zero trials when the persistent cache is warm),
    ``--no-tune`` pins the static calibrated picks, ``--tune-cache``
    redirects the persistent config cache for this run."""
    g = p.add_mutually_exclusive_group()
    g.add_argument(
        "--tune",
        action="store_true",
        help="autotune this workload on-device first (cached: second run "
        "does zero trials)",
    )
    g.add_argument(
        "--no-tune",
        action="store_true",
        help="ignore tuned configs; use the static calibrated defaults",
    )
    p.add_argument(
        "--tune-cache",
        default=None,
        metavar="DIR",
        help="tuned-config cache dir (default: STENCIL_TUNE_CACHE or "
        "~/.cache/stencil_tpu/tune)",
    )


def add_exchange_route_flag(p: argparse.ArgumentParser) -> None:
    """``--exchange-route``: pin the halo exchange's y/z-sweep route for
    this run (docs/tuning.md "Exchange routes").  ``auto`` (default) keeps
    the planner resolution: ``STENCIL_EXCHANGE_ROUTE`` > tuned config > the
    static ``direct`` fallback."""
    from stencil_tpu.ops.exchange import EXCHANGE_ROUTES

    p.add_argument(
        "--exchange-route",
        default="auto",
        choices=("auto",) + EXCHANGE_ROUTES,
        help="y/z-sweep exchange route: direct slabs vs the packed z-shell "
        "(zpack_*) or y+z-shell (yzpack_*) messages (auto = env > tuned "
        "config > direct)",
    )


def apply_exchange_route(args, dd) -> None:
    """Apply ``add_exchange_route_flag``'s choice to a pre-realize domain."""
    route = getattr(args, "exchange_route", "auto")
    if route != "auto":
        dd.set_exchange_route(route)


def add_kernel_axis_flags(p: argparse.ArgumentParser) -> None:
    """``--compute-unit`` / ``--storage-dtype``: pin the level kernels'
    execution unit and the field buffers' storage dtype for this run
    (docs/tuning.md "Compute unit and storage dtype").  ``auto`` (default)
    keeps the planner resolution: ``STENCIL_COMPUTE_UNIT`` /
    ``STENCIL_STORAGE_DTYPE`` > tuned config > the static ``vpu`` /
    ``native`` fallbacks; structural guards (non-f32 fields, routes with no
    contraction/f32-accumulate kernels) degrade with a warning."""
    p.add_argument(
        "--compute-unit",
        default="auto",
        choices=("auto", "vpu", "mxu", "mxu_band"),
        help="level-kernel execution unit: vpu roll+add chain vs one banded "
        "contraction per axis on the MXU — dense circulant (mxu) or the "
        "blocked (2r+1)-band tiling (mxu_band, ~n/(2r+1)x fewer FLOPs) "
        "(auto = env > tuned config > vpu)",
    )
    p.add_argument(
        "--mxu-input",
        default="auto",
        choices=("auto", "f32", "bf16"),
        help="MXU contraction operand precision: bf16 inputs double the "
        "matrix unit's FLOP ratio under the unchanged f32-accumulate "
        "contract (auto = env > tuned config > f32; inert under vpu)",
    )
    p.add_argument(
        "--storage-dtype",
        default="auto",
        choices=("auto", "native", "bf16"),
        help="field-buffer storage: native dtype vs bf16 storage with f32 "
        "accumulation in-kernel — half the bytes/cell (auto = env > tuned "
        "config > native)",
    )


def kernel_axis_kwargs(args) -> dict:
    """Model ctor kwargs from ``add_kernel_axis_flags``'s choices (``auto``
    maps to None = consult the resolution chain)."""
    out = {}
    cu = getattr(args, "compute_unit", "auto")
    mi = getattr(args, "mxu_input", "auto")
    sd = getattr(args, "storage_dtype", "auto")
    if cu != "auto":
        out["compute_unit"] = cu
    if mi != "auto":
        out["mxu_input"] = mi
    if sd != "auto":
        out["storage_dtype"] = sd
    return out


def add_stream_overlap_flag(p: argparse.ArgumentParser) -> None:
    """``--stream-overlap``: pin the stream engine's split-step overlap
    schedule for this run (docs/tuning.md "Stream overlap").  ``auto``
    (default) keeps the planner resolution: ``STENCIL_STREAM_OVERLAP`` >
    tuned config > the static ``off``."""
    p.add_argument(
        "--stream-overlap",
        default="auto",
        choices=("auto", "off", "split"),
        help="stream-engine overlap schedule: off = exchange-then-compute, "
        "split = interior pass concurrent with the shell ppermutes plus a "
        "narrow exterior fix-up (bitwise-identical; auto = env > tuned "
        "config > off)",
    )


def add_stream_halo_flag(p: argparse.ArgumentParser) -> None:
    """``--stream-halo``: pin the stream engine's halo consumption mode for
    this run (docs/tuning.md "Fused halo consumption").  ``auto`` (default)
    keeps the planner resolution: ``STENCIL_STREAM_HALO`` > tuned config >
    the static ``array``."""
    p.add_argument(
        "--stream-halo",
        default="auto",
        choices=("auto", "array", "fused"),
        help="stream-engine halo consumption: array = unpack received "
        "shells into the big arrays, fused = land the packed yzpack_* "
        "messages directly in the pass's VMEM planes (bitwise-identical; "
        "needs --exchange-route yzpack_*; auto = env > tuned config > "
        "array)",
    )


def add_numerics_flag(p: argparse.ArgumentParser) -> None:
    """``--numerics-every``: the numerics observatory's snapshot cadence
    (docs/observability.md "Numerics observatory").  Every N raw steps ONE
    fused on-device dispatch computes per-quantity interior health
    (min/max/absmax/mean/L2/non-finite count + first-non-finite
    coordinate), lands it in the snapshot ring (heartbeats and crash
    reports carry it), and runs the registered invariant guardbands —
    observe-only unless ``STENCIL_NUMERICS_ABORT=1``.  Unset falls back to
    ``STENCIL_NUMERICS_EVERY``; 0 disables."""
    p.add_argument(
        "--numerics-every",
        type=int,
        default=None,
        metavar="N",
        help="fused on-device field-health snapshot every N raw steps "
        "(default: STENCIL_NUMERICS_EVERY; 0 = off; see "
        "docs/observability.md 'Numerics observatory')",
    )


def apply_numerics(args, dd) -> None:
    """Apply ``add_numerics_flag``'s choice to a domain (the env default
    is already read by the domain's constructor)."""
    every = getattr(args, "numerics_every", None)
    if every is not None:
        dd.set_numerics_every(max(every, 0))


def add_checkpoint_flags(p: argparse.ArgumentParser) -> None:
    """Long-run survival knobs shared by the model drivers
    (docs/resilience.md "Long-run operation"): ``--checkpoint-dir`` turns
    on the checkpoint/resume supervisor for the run (retention ring of
    atomic checkpoints, SIGTERM-preemption final save + resumable exit,
    FATAL/STALL restart budget), ``--checkpoint-every`` sets the step
    cadence, ``--resume`` continues from the newest valid ring entry.
    Unset knobs fall back to the ``STENCIL_CHECKPOINT_*`` /
    ``STENCIL_SUPERVISOR_RESTARTS`` environment (validated reads)."""
    p.add_argument(
        "--checkpoint-dir",
        default=None,
        metavar="DIR",
        help="checkpoint ring directory; enables the run supervisor "
        "(reuse an existing ring only together with --resume)",
    )
    p.add_argument(
        "--checkpoint-every",
        type=int,
        default=None,
        metavar="N",
        help="checkpoint every N iterations (default: STENCIL_CHECKPOINT_EVERY)",
    )
    p.add_argument(
        "--checkpoint-keep",
        type=int,
        default=None,
        metavar="K",
        help="retention-ring size (default: STENCIL_CHECKPOINT_KEEP or 3)",
    )
    p.add_argument(
        "--resume",
        action="store_true",
        help="resume from the newest valid checkpoint in --checkpoint-dir "
        "(corrupt entries fall back to older ones)",
    )


def supervisor_for(args, dd, label: str, run_state=None, on_mesh_change=None):
    """A configured ``RunSupervisor`` from ``add_checkpoint_flags``'s
    choices (environment knobs fill unset flags), or None when no
    checkpoint dir is configured anywhere — supervision is opt-in.
    ``on_mesh_change`` is the elastic-capacity rebuild hook (the models'
    ``rebuild_after_reshard``): called after a drain-and-reshard or a
    cross-mesh restore so steps closed over the old mesh are re-traced."""
    from stencil_tpu.resilience.supervisor import RunSupervisor, SupervisorConfig

    overrides = {}
    if getattr(args, "checkpoint_every", None) is not None:
        overrides["every_steps"] = max(args.checkpoint_every, 0)
    if getattr(args, "checkpoint_keep", None) is not None:
        overrides["keep"] = max(args.checkpoint_keep, 1)
    cfg = SupervisorConfig.from_env(
        dir=getattr(args, "checkpoint_dir", None), **overrides
    )
    if cfg is None:
        return None
    return RunSupervisor(
        dd, cfg, label=label, run_state=run_state,
        on_mesh_change=on_mesh_change,
    )


def tune_begin(args) -> None:
    """Apply the ``add_tune_flags`` choices to the tune facade; call right
    after ``parse_args`` (before any model/planner construction).  Pair
    with ``tune_end`` on the exit path — the overrides are process-global
    and sequential in-process driver runs (tests) must not inherit a prior
    run's ``--no-tune``/``--tune-cache``."""
    from stencil_tpu import tune

    args._tune_restore = tune.overrides()
    if getattr(args, "tune_cache", None):
        tune.set_cache_dir(args.tune_cache)
    if getattr(args, "no_tune", False):
        tune.set_enabled(False)
    elif getattr(args, "tune", False):
        tune.set_enabled(True)


def tune_end(args) -> None:
    from stencil_tpu import tune

    state = getattr(args, "_tune_restore", None)
    if state is not None:
        tune.restore(state)
        args._tune_restore = None


def tune_report_stderr(report) -> None:
    """One stderr line summarizing a driver's autotune outcome."""
    import sys

    print(
        f"tune[{report.key.route}]: source={report.source} "
        f"config={report.config} trials={report.trials} "
        f"pruned={report.pruned}",
        file=sys.stderr,
    )


def _write_snapshot(path: str) -> None:
    from stencil_tpu import telemetry
    from stencil_tpu.utils.artifact import atomic_write_json

    atomic_write_json(path, telemetry.snapshot())


def telemetry_begin(args) -> None:
    """Enable telemetry when ``--metrics-out`` asked for it (env knobs may
    have enabled it already); call right after ``parse_args``.

    An owned run starts from zeroed metrics (sequential in-process driver
    mains must not bleed counters into each other's snapshots), and the
    snapshot write is ALSO registered via ``atexit`` so a CLI run that dies
    on an exception still leaves its post-mortem artifact — the failed runs
    are the ones whose retry/descent counters matter most.  The clean path
    (``telemetry_end``) writes and unregisters."""
    from stencil_tpu import telemetry

    path = getattr(args, "metrics_out", None)
    if path and not telemetry.enabled():
        telemetry.enable()
        telemetry.reset()
        args._telemetry_owned = True
    if path:
        import atexit

        args._telemetry_atexit = lambda: _write_snapshot(path)
        atexit.register(args._telemetry_atexit)


def telemetry_end(args, profile_capture=None) -> None:
    """Flush telemetry artifacts and write the ``--metrics-out`` snapshot on
    ``main``'s clean exit path (the atexit hook covers crashed CLI runs).
    ``profile_capture`` hands the driver's ``ProfileCapture`` in so the
    device-row merge runs AFTER the final Chrome-trace dump — the other
    order would re-dump host-only spans over the merged timeline."""
    from stencil_tpu import telemetry

    arts = {}
    if telemetry.enabled():
        arts = telemetry.write_artifacts()
    if profile_capture is not None:
        profile_finalize(args, profile_capture, chrome_path=arts.get("trace"))
    path = getattr(args, "metrics_out", None)
    if path:
        _write_snapshot(path)
    hook = getattr(args, "_telemetry_atexit", None)
    if hook is not None:
        import atexit

        atexit.unregister(hook)
        args._telemetry_atexit = None
    if getattr(args, "_telemetry_owned", False):
        # leave the process-global state as we found it (in-process callers:
        # tests drive driver mains directly)
        telemetry.disable()


def host_round_trip_s() -> float:
    """Latency of one device->host readback (large through a tunneled dev
    backend; subtract it from device-looped timings — see bench.py)."""
    import jax.numpy as jnp

    x = jnp.zeros((8,))
    float(jnp.sum(x))
    t0 = time.perf_counter()
    for _ in range(5):
        float(jnp.sum(x))
    return (time.perf_counter() - t0) / 5


def timed_inner_loop(run, inner: int, rt: float, n_iters: int,
                     min_ratio: float = 5.0, max_inner: int = 1 << 14):
    """Per-iteration seconds for a device-looped benchmark on a tunneled
    backend, with the host round trip ``rt`` subtracted SAFELY.

    ``run(k)`` must execute one synchronous dispatch of ``k`` inner
    iterations (jit-cached per static ``k``).  The measured rt has 2-3x
    variance on tunneled backends, so a fixed ``inner`` can make ``t - rt``
    go negative and clamp to 0.0 (infinite B/s).  This helper auto-scales
    ``inner`` until one dispatch takes >= ``min_ratio * rt``, re-warming
    after each growth so compiles stay out of the timing; if the threshold
    is unreachable it reports the raw (un-subtracted) time with a warning
    rather than a clamped sample.  Returns (samples, inner_used).
    """
    import sys

    run(inner)  # compile/warm at this inner count
    while True:
        t0 = time.perf_counter()
        run(inner)
        t = time.perf_counter() - t0
        if t >= min_ratio * rt or inner >= max_inner:
            break
        grow = max(2 * inner, int(inner * min_ratio * rt / max(t, 1e-9)))
        inner = min(grow, max_inner)
        run(inner)  # compile at the new static count before re-measuring
    samples = []
    subtract = t >= min_ratio * rt
    if not subtract:
        print(
            f"warning: dispatch ({t:.3g}s at inner={inner}) not >> host rt "
            f"({rt:.3g}s); reporting raw per-iter time (rt not subtracted)",
            file=sys.stderr,
        )
    for _ in range(n_iters):
        t0 = time.perf_counter()
        run(inner)
        t = time.perf_counter() - t0
        samples.append(((t - rt) if subtract else t) / inner)
    return samples, inner


def ranks_and_devcount():
    """(MPI size, per-process device count) analogs."""
    return jax.process_count(), jax.local_device_count()


def fit_to_mesh(x: int, y: int, z: int, radius, devices=None):
    """Round each axis to the NEAREST multiple of the mesh dim (reference
    subdomains may be uneven, partition.hpp:83-114; XLA shards may not — the
    nearest divisible size keeps weak-scaled runs comparable).  The per-axis
    shard is clamped up to the radius shell so realize() cannot reject it."""
    from stencil_tpu.parallel.mesh import choose_partition

    if devices is None:
        devices = jax.devices()
    part = choose_partition((x, y, z), radius, devices)
    dim = part.dim()
    lo, hi = radius.lo(), radius.hi()
    min_shard = max(lo.x, lo.y, lo.z, hi.x, hi.y, hi.z, 1)
    return tuple(
        max(round(v / d), min_shard) * d for v, d in zip((x, y, z), dim)
    )


def make_edge_transfer(mesh, n_dev: int, src: int, dst: int, n_elems: int):
    """Jitted single-edge ``lax.ppermute`` src->dst of ``n_elems`` f32 per
    shard, plus a matching input array.  The shared point-to-point primitive
    under pingpong / bench-alltoallv / measure-buf-exchange."""
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P("d"))

    @jax.jit
    def go(x):
        def f(blk):
            return lax.ppermute(blk, "d", [(src, dst)])

        return shard_map(f, mesh=mesh, in_specs=P("d"), out_specs=P("d"))(x)

    x = jax.device_put(jnp.ones((n_elems * n_dev,), jnp.float32), sharding)
    return go, x


def _dst_unique_rounds(pairs):
    """Split (src, dst, nbytes) pairs into minimal groups where each source
    and each destination appears at most once — ``lax.ppermute`` requires
    unique sources and destinations per collective.  All groups still launch
    in ONE dispatch."""
    rounds = []
    for p in pairs:
        for r in rounds:
            if all(q[1] != p[1] and q[0] != p[0] for q in r):
                r.append(p)
                break
        else:
            rounds.append([p])
    return rounds


def make_matrix_transfer(mesh, comm):
    """Jitted CONTENDED traversal of a bytes matrix: every pair's transfer is
    in flight in one dispatch, so the fabric sees all copies at once — the
    TPU expression of the reference's batch-started concurrent copies
    (bench_alltoallv.cu:139-168 all-pairs streams, measure_buf_exchange.cu:
    120-159 latch-kernel batch start).  Pairs are grouped by payload size
    (one input buffer per size class, shared by its collectives) and by
    unique-destination rounds (a ppermute constraint); XLA's async collective
    scheduling overlaps the lot.  Returns (go, bufs): ``go(*bufs)`` runs one
    traversal; time it with block_until_ready."""
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_dev = comm.shape[0]
    pairs = [
        (i, j, int(comm[i, j]))
        for i in range(n_dev)
        for j in range(n_dev)
        if i != j and comm[i, j] > 0
    ]
    if not pairs:
        return None, ()
    sizes = sorted({sz for _, _, sz in pairs})
    rounds_by_size = {
        sz: _dst_unique_rounds([p for p in pairs if p[2] == sz]) for sz in sizes
    }
    sharding = NamedSharding(mesh, P("d"))
    bufs = tuple(
        jax.device_put(
            jnp.ones((max(sz // 4, 1) * n_dev,), jnp.float32), sharding
        )
        for sz in sizes
    )

    @jax.jit
    def go(*arrs):
        def f(*blks):
            outs = []
            for blk, sz in zip(blks, sizes):
                for rnd in rounds_by_size[sz]:
                    outs.append(
                        lax.ppermute(blk, "d", [(i, j) for i, j, _ in rnd])
                    )
            return tuple(outs)

        return shard_map(
            f,
            mesh=mesh,
            in_specs=tuple(P("d") for _ in arrs),
            out_specs=tuple(
                P("d") for sz in sizes for _ in rounds_by_size[sz]
            ),
        )(*arrs)

    return go, bufs


def measure_matrix_concurrent(mesh, comm, n_iters: int) -> float:
    """Seconds for one CONTENDED traversal of the bytes matrix (all pairs in
    flight together; see make_matrix_transfer).  Compile excluded."""
    go, bufs = make_matrix_transfer(mesh, comm)
    if go is None:
        return 0.0
    jax.block_until_ready(go(*bufs))
    t0 = time.perf_counter()
    for _ in range(n_iters):
        out = go(*bufs)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n_iters


def measure_edge(mesh, n_dev: int, src: int, dst: int, nbytes: int, n_iters: int) -> float:
    """Seconds per single-edge transfer of ``nbytes`` (compile excluded)."""
    import time

    go, x = make_edge_transfer(mesh, n_dev, src, dst, max(int(nbytes) // 4, 1))
    go(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(n_iters):
        y = go(x)
    y.block_until_ready()
    return (time.perf_counter() - t0) / n_iters


class WallTimer:
    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *a):
        self.elapsed = time.perf_counter() - self.t0
