"""Driver applications (reference ``bin/``): same CLIs, same CSV schemas."""
