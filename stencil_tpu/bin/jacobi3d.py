"""jacobi3d driver — the flagship benchmark.

Parity target: reference bin/jacobi3d.cu.  Same CLI shape (positional x y z
base size, weak-scaled by numSubdoms^(1/3); --no-overlap; --trivial; method
flags; --paraview/--prefix/--period) and the same CSV row:

    jacobi3d,<methods>,ranks,devCount,x,y,z,min(s),trimean(s)

(jacobi3d.cu:378-379).  Per-iteration time is the max across processes of the
wall time around step+sync (jacobi3d.cu:265-341).
"""

from __future__ import annotations

import argparse
import contextlib
import sys
import time

import jax
import jax.numpy as jnp

from stencil_tpu.bin import _common
from stencil_tpu.models.jacobi import Jacobi3D, weak_scaled_size
from stencil_tpu.utils.statistics import Statistics


def main(argv=None) -> int:
    p = argparse.ArgumentParser("jacobi3d")
    _common.add_method_flags(p)
    p.add_argument("--no-overlap", action="store_true", help="Don't overlap communication and computation")
    p.add_argument("--prefix", default="", help="prefix for paraview files")
    p.add_argument("--paraview", action="store_true", help="dump paraview files")
    p.add_argument("--iters", "-n", type=int, default=30, help="number of iterations")
    p.add_argument("--period", "-q", type=int, default=-1, help="iterations between checkpoints")
    p.add_argument("--no-weak-scale", action="store_true", help="use x y z as the global size directly")
    p.add_argument("--trace", default=None, help="write a jax.profiler trace to this dir (nsys analog)")
    p.add_argument("--plan", action="store_true", help="dump the communication plan (plan_<rank>.txt analog)")
    p.add_argument("--halo-multiplier", type=int, default=1, help="exchange every k steps with k*r halos")
    p.add_argument(
        "--kernel-impl",
        choices=["pallas", "jnp"],
        default="pallas",
        help="pallas plane-streaming kernel (fast) or XLA slices",
    )
    p.add_argument(
        "--dtype",
        choices=["float32", "bfloat16"],
        default="float32",
        help="quantity dtype (bfloat16: precision-reduced, ~1.6x on v5e)",
    )
    p.add_argument(
        "--pallas-path",
        choices=["auto", "wrap", "slab", "shell", "wavefront"],
        default="auto",
        help="force a specific pallas route (auto: wrap single-device, "
        "temporally-blocked wavefront multi-device, slab/shell fallbacks)",
    )
    p.add_argument(
        "--overlap-report",
        action="store_true",
        help="time overlap=True vs overlap=False (jnp kernel) and report the "
        "achieved-overlap delta (reference --no-overlap A/B, jacobi3d.cu:265-337)",
    )
    _common.add_telemetry_flags(p)
    _common.add_profile_flags(p)
    _common.add_tune_flags(p)
    _common.add_exchange_route_flag(p)
    _common.add_kernel_axis_flags(p)
    _common.add_numerics_flag(p)
    _common.add_checkpoint_flags(p)
    p.add_argument("x", type=int, nargs="?", default=512)
    p.add_argument("y", type=int, nargs="?", default=512)
    p.add_argument("z", type=int, nargs="?", default=512)
    args = p.parse_args(argv)
    _common.telemetry_begin(args)
    _common.tune_begin(args)
    try:
        # the tune overrides are process-global; restore them whatever
        # happens so sequential in-process runs (tests) never inherit a
        # prior run's --no-tune/--tune-cache
        return _run(args)
    finally:
        _common.tune_end(args)


def _run(args) -> int:
    x, y, z = _global_size(args)
    if args.overlap_report:
        rc = _overlap_report(args, x, y, z)
        _common.telemetry_end(args)
        return rc

    checkpoint_period = args.period if args.period > 0 else max(args.iters // 10, 1)

    # uneven sizes are padded-and-masked by realize(); no size adjustment
    kernel_impl = args.kernel_impl
    if kernel_impl == "pallas" and (args.halo_multiplier > 1 or args.no_overlap):
        # the pallas path is a fused radius-1 single-exchange kernel; the
        # halo multiplier and the overlap on/off comparison only exist in the
        # generic make_step machinery
        print(
            "halo-multiplier/--no-overlap force --kernel-impl jnp", file=sys.stderr
        )
        kernel_impl = "jnp"
    if (
        args.tune
        and kernel_impl == "pallas"
        and args.pallas_path in ("auto", "wrap", "wavefront")
    ):  # slab/shell routes have no tunable axes — nothing would consult
        # populate the tuned-config cache for THIS workload before the model
        # builds (the build consults it); a warm cache runs zero trials.
        # Gated on the POST-force kernel_impl: a jnp run never consults the
        # tuner, so searching for it would be pure wasted device work.
        # Search selection follows the route the MODEL will take (the wrap
        # route only exists single-device; auto picks wrap there and the
        # wavefront otherwise) — searching a route the build won't consult
        # would burn device work on an orphaned cache entry.
        from stencil_tpu.tune import runners as tune_runners

        interp = jax.default_backend() == "cpu"
        single = len(jax.devices()) == 1
        if args.pallas_path == "wrap" or (args.pallas_path == "auto" and single):
            if not single:
                print(
                    "--tune skipped: pallas_path='wrap' needs a single "
                    "device (the model build will reject it too)",
                    file=sys.stderr,
                )
                report = None
            else:
                report = tune_runners.autotune_jacobi_wrap(
                    x, y, z, dtype=jnp.dtype(args.dtype), interpret=interp
                )
        else:  # forced wavefront, or auto on a multi-device mesh
            report = tune_runners.autotune_jacobi_wavefront(
                x, y, z, dtype=jnp.dtype(args.dtype), interpret=interp,
                # same placement as the model built below — a strategy
                # mismatch would re-key the workload and orphan the search
                strategy=_common.parse_strategy(args),
            )
        if report is not None:
            _common.tune_report_stderr(report)
    elif args.tune and kernel_impl == "jnp":
        # the jnp engine's macro step runs the GENERIC exchange — tune its
        # z-sweep route (direct vs packed z-shell, docs/tuning.md "Exchange
        # routes") so the model's realize picks the measured winner up.  The
        # cache is checked BEFORE the probe domain realizes (tune_key works
        # pre-realize), so a warm-cache --tune run does zero device work;
        # the probe is freed before the model allocates.
        from stencil_tpu import tune
        from stencil_tpu.core.radius import Radius
        from stencil_tpu.domain import DistributedDomain
        from stencil_tpu.tune import runners as tune_runners

        probe = DistributedDomain(x, y, z)
        r = Radius.constant(0)
        r.set_face(1)  # the jacobi radius (jacobi3d.cu:205-214)
        probe.set_radius(r)
        probe.set_placement(_common.parse_strategy(args))
        probe.add_data("temp", dtype=jnp.dtype(args.dtype))
        if args.halo_multiplier > 1:
            probe.set_halo_multiplier(args.halo_multiplier)
        if tune.best_config(probe.tune_key("exchange")) is not None:
            print("tune[exchange]: source=cache (warm; zero trials)", file=sys.stderr)
        else:
            probe.realize()
            _common.tune_report_stderr(tune_runners.autotune_exchange(probe))
        del probe
    model = Jacobi3D(
        x,
        y,
        z,
        overlap=not args.no_overlap,
        strategy=_common.parse_strategy(args),
        methods=_common.parse_methods(args),
        kernel_impl=kernel_impl,
        interpret=jax.default_backend() == "cpu",
        pallas_path=args.pallas_path,
        dtype=jnp.dtype(args.dtype),
        **_common.kernel_axis_kwargs(args),
    )
    if args.halo_multiplier > 1:
        model.dd.set_halo_multiplier(args.halo_multiplier)
    _common.apply_exchange_route(args, model.dd)
    _common.apply_numerics(args, model.dd)
    model.realize()
    if args.plan:
        print(f"wrote {model.dd.write_plan(args.prefix + 'plan')}", file=sys.stderr)

    iter_time = Statistics()
    prof = _common.profile_capture_for(args)
    sup = _common.supervisor_for(
        args,
        model.dd,
        label="jacobi",
        run_state=lambda: {
            "model": "jacobi3d",
            "kernel_impl": kernel_impl,
            "compute_unit": model._compute_unit,
            "iters": args.iters,
        },
        # elastic capacity: a drain-and-reshard (or cross-mesh restore)
        # re-traces the step for the new geometry
        on_mesh_change=model.rebuild_after_reshard,
    )
    mult = args.halo_multiplier
    dispatch_index = [0]

    def timed_iter():
        # cadence device-profile capture around the dispatch (a captured
        # iteration's timing sample carries profiler overhead — profiling
        # is opt-in and the steady-state stats absorb one outlier)
        idx = dispatch_index[0]
        dispatch_index[0] += 1
        with (prof.maybe(idx) if prof is not None else contextlib.nullcontext()):
            t0 = time.perf_counter()
            model.step(mult)
            model.block_until_ready()
            # one macro (halo_multiplier raw iterations) per timed step; the
            # CSV stays per-iteration so rows are comparable across multipliers
            iter_time.insert((time.perf_counter() - t0) / mult)

    from stencil_tpu.telemetry import trace

    rc = 0
    if sup is not None:
        # supervised long run: no separate warm-up dispatch — a resumed
        # process must advance EXACTLY (iters - restored) iterations for
        # kill/resume runs to stay bitwise comparable to unkilled ones
        # (scripts/run_soak.py); the first timed sample absorbs the compile
        def advance(n):
            for _ in range(n):
                timed_iter()

        def on_chunk(done, n):
            # same 0-based frame indices as the unsupervised loop (chunk=1:
            # `it = done - n` is the iteration that just completed)
            it = done - n
            if args.paraview and it % checkpoint_period == 0:
                from stencil_tpu.io.paraview import write_paraview

                write_paraview(model.dd, f"{args.prefix}jacobi3d_{it}")

        with trace(args.trace):
            out = sup.run(
                args.iters,
                advance,
                start_step=None if args.resume else 0,
                chunk=1,
                on_chunk=on_chunk,
            )
        rc = out.exit_code
    else:
        model.step(mult)  # compile outside the timed loop
        model.block_until_ready()
        with trace(args.trace):
            for it in range(args.iters):
                timed_iter()
                if args.paraview and it % checkpoint_period == 0:
                    from stencil_tpu.io.paraview import write_paraview

                    write_paraview(model.dd, f"{args.prefix}jacobi3d_{it}")
    if args.paraview:
        from stencil_tpu.io.paraview import write_paraview

        write_paraview(model.dd, f"{args.prefix}jacobi3d_final")

    if jax.process_index() == 0 and iter_time.count() > 0:
        ranks, dev_count = _common.ranks_and_devcount()
        print(
            f"jacobi3d,{_common.method_str(args)},{ranks},{dev_count},"
            f"{x},{y},{z},{iter_time.min()},{iter_time.trimean()}"
        )
    _common.telemetry_end(args, profile_capture=prof)
    return rc


def _global_size(args):
    """CLI base size -> global size, weak-scaled by numSubdoms^(1/3)
    (jacobi3d.cu:167-169) unless --no-weak-scale."""
    if args.no_weak_scale:
        return args.x, args.y, args.z
    n = len(jax.devices())
    return tuple(weak_scaled_size(v, n) for v in (args.x, args.y, args.z))


def _overlap_report(args, x, y, z) -> int:
    """A/B the interior/exterior overlap split on this hardware: identical
    jnp-kernel models, overlap on vs off, one timing line each plus the
    ratio.  The scheduled-HLO interleaving itself is pinned by
    tests/test_overlap_schedule.py; this reports the achieved wall-clock
    effect (the reference measures the same thing by rerunning with
    --no-overlap)."""
    rt = _common.host_round_trip_s()

    def measure(overlap):
        # scoped so the first model's HBM is freed before the second
        # realize() allocates (the A/B must fit where a single run fits)
        model = Jacobi3D(
            x, y, z,
            overlap=overlap,
            strategy=_common.parse_strategy(args),
            methods=_common.parse_methods(args),
            kernel_impl="jnp",
        )
        model.realize()

        def run(k):
            model.step(k)
            model.block_until_ready()

        samples, _ = _common.timed_inner_loop(run, 10, rt, args.iters)
        return min(samples)

    results = {overlap: measure(overlap) for overlap in (True, False)}
    if jax.process_index() == 0:
        t_on, t_off = results[True], results[False]
        print(
            f"overlap-report,{x},{y},{z},{t_on},{t_off},"
            f"{(t_off - t_on) / t_off if t_off > 0 else 0.0:.4f}"
        )
        print(
            f"# overlap=True {t_on*1e3:.3f} ms/iter; overlap=False "
            f"{t_off*1e3:.3f} ms/iter; saved {(t_off-t_on)*1e3:.3f} ms "
            f"({100*(t_off-t_on)/t_off if t_off > 0 else 0:.1f}%)",
            file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
