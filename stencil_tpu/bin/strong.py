"""strong — exchange-only strong-scaling benchmark (+ overlap A/B).

Parity target: reference bin/strong.cu: identical to weak.cu but the global
size is NOT scaled by the device count (strong.cu:30-48; defaults 512^3).
Same CSV row layout (the reference even prints "weak," for the strong binary,
strong.cu:181 — we emit "strong," so rows are distinguishable).

``--overlap`` runs the same stream-engine split-vs-off A/B as weak.py, at
the FIXED global size (rounded to the forced/derived mesh) — the
strong-scaling rows of the overlap story.  ``--tune`` wires both drivers
into the autotuner's exchange-route and stream-plan searches (bin/weak.py).
"""

from __future__ import annotations

import sys

import jax

from stencil_tpu.bin import _common
from stencil_tpu.bin.weak import build_parser, emit_overlap, run, run_overlap
from stencil_tpu.core.radius import Radius


def main(argv=None) -> int:
    args = build_parser("strong").parse_args(argv)
    args.trivial = args.naive
    _common.telemetry_begin(args)
    _common.tune_begin(args)
    try:
        if args.overlap:
            emit_overlap(
                run_overlap(args, name="strong", weak_scale=False), args
            )
            _common.telemetry_end(args)
            return 0
        x, y, z = _common.fit_to_mesh(args.x, args.y, args.z, Radius.constant(3))
        row = run(x, y, z, args.n_iters, args, name="strong")
        if jax.process_index() == 0:
            print(row)
        _common.telemetry_end(args)
        return 0
    finally:
        _common.tune_end(args)


if __name__ == "__main__":
    sys.exit(main())
