"""strong — exchange-only strong-scaling benchmark.

Parity target: reference bin/strong.cu: identical to weak.cu but the global
size is NOT scaled by the device count (strong.cu:30-48; defaults 512^3).
Same CSV row layout (the reference even prints "weak," for the strong binary,
strong.cu:181 — we emit "strong," so rows are distinguishable).
"""

from __future__ import annotations

import sys

import jax

from stencil_tpu.bin import _common
from stencil_tpu.bin.weak import build_parser, run
from stencil_tpu.core.radius import Radius


def main(argv=None) -> int:
    args = build_parser("strong").parse_args(argv)
    args.trivial = args.naive
    _common.telemetry_begin(args)
    x, y, z = _common.fit_to_mesh(args.x, args.y, args.z, Radius.constant(3))
    row = run(x, y, z, args.n_iters, args, name="strong")
    if jax.process_index() == 0:
        print(row)
    _common.telemetry_end(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
