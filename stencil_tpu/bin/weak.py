"""weak — exchange-only weak-scaling benchmark.

Parity target: reference bin/weak.cu.  Same shape: positional ``x y z nIters``
base size weak-scaled by ``numGpus^(1/3)`` (weak.cu:63-65), radius 3, four
float quantities (weak.cu:120,132-135), nIters of exchange+swap, then one CSV
row of bytes-per-method + all setup/exchange timers (weak.cu:173-194):

    weak,<methods>,x,y,z,s,MPI(B),Colocated(B),cudaMemcpyPeer(B),direct(B),
    iters,gpus,nodes,ranks,mpi_topo,node_gpus,peer_en,placement,realize,plan,
    create,exchange,swap

On TPU all exchange bytes ride the collective path, so they are reported in
the MPI(B) column (the reference's "All"-method column layout is preserved for
script compatibility); peer_en/node_gpus phases don't exist and report 0.
"""

from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp

from stencil_tpu.bin import _common
from stencil_tpu.core.radius import Radius
from stencil_tpu.domain import DistributedDomain
from stencil_tpu.models.jacobi import weak_scaled_size
from stencil_tpu.utils.config import MethodFlags


def run(x: int, y: int, z: int, n_iters: int, args, name: str = "weak") -> str:
    dd = DistributedDomain(x, y, z)
    dd.set_methods(_common.parse_methods(args))
    dd.set_radius(Radius.constant(3))  # weak.cu:120
    dd.set_placement(_common.parse_strategy(args))
    _common.apply_exchange_route(args, dd)
    for i in range(4):  # weak.cu:132-135
        dd.add_data(f"d{i}", dtype=jnp.float32)
    dd.enable_exchange_stats(True)
    dd.realize()

    for _ in range(n_iters):
        dd.exchange()
        dd.swap()

    ranks, dev_count = _common.ranks_and_devcount()
    num_gpus = ranks * dev_count
    num_nodes = ranks
    s = dd.stats
    # Colocated/Peer/Direct byte columns are literal 0: those transports do
    # not exist on TPU — every byte rides the collective and is reported in
    # the MPI(B) column (the reference sums per-method counters,
    # src/stencil.cu:260-361)
    row = (
        f"{name},{_common.method_str(args)},{x},{y},{z},{x * y * z},"
        f"{dd.exchange_bytes_for_method(MethodFlags.CudaMpi)},"
        f"0,0,0,"
        f"{n_iters},{num_gpus},{num_nodes},{ranks},"
        f"{s.time_topo:e},{0.0:e},{0.0:e},{s.time_placement:e},"
        f"{s.time_realize:e},{s.time_plan:e},{s.time_create:e},"
        f"{s.time_exchange:e},{s.time_swap:e}"
    )
    return row


def build_parser(name: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(name)
    p.add_argument("x", type=int, nargs="?", default=512)
    p.add_argument("y", type=int, nargs="?", default=512)
    p.add_argument("z", type=int, nargs="?", default=512)
    p.add_argument("n_iters", type=int, nargs="?", default=30)
    p.add_argument("--kernel", action="store_true")
    p.add_argument("--peer", action="store_true")
    p.add_argument("--colo", action="store_true")
    p.add_argument("--naive", action="store_true", help="trivial placement (weak.cu --naive)")
    p.add_argument("--cuda-aware", dest="cuda_aware_mpi", action="store_true")
    p.add_argument("--staged", action="store_true")
    # no tune flags here: weak/strong have no search of their own (--tune
    # would be a misleading no-op) — but the exchange PLANNER does consult
    # the tuned exchange-route config at realize() since the exchange-route
    # PR; --exchange-route pins it per run
    _common.add_exchange_route_flag(p)
    _common.add_telemetry_flags(p)
    return p


def main(argv=None) -> int:
    args = build_parser("weak").parse_args(argv)
    args.trivial = args.naive
    _common.telemetry_begin(args)
    devs = len(jax.devices())
    # weak.cu:63-65 round-to-nearest scaling
    x = weak_scaled_size(args.x, devs)
    y = weak_scaled_size(args.y, devs)
    z = weak_scaled_size(args.z, devs)
    x, y, z = _common.fit_to_mesh(x, y, z, Radius.constant(3))
    print(
        f"{devs} subdomains: {x},{y},{z}={x * y * z}",
        file=sys.stderr,
    )
    row = run(x, y, z, args.n_iters, args, name="weak")
    if jax.process_index() == 0:
        print(row)
    _common.telemetry_end(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
