"""weak — weak-scaling benchmark: exchange-only parity CSV + overlap A/B.

Parity target: reference bin/weak.cu.  Same shape: positional ``x y z nIters``
base size weak-scaled by ``numGpus^(1/3)`` (weak.cu:63-65), radius 3, four
float quantities (weak.cu:120,132-135), nIters of exchange+swap, then one CSV
row of bytes-per-method + all setup/exchange timers (weak.cu:173-194):

    weak,<methods>,x,y,z,s,MPI(B),Colocated(B),cudaMemcpyPeer(B),direct(B),
    iters,gpus,nodes,ranks,mpi_topo,node_gpus,peer_en,placement,realize,plan,
    create,exchange,swap

On TPU all exchange bytes ride the collective path, so they are reported in
the MPI(B) column (the reference's "All"-method column layout is preserved for
script compatibility); peer_en/node_gpus phases don't exist and report 0.

Beyond the reference: ``--overlap`` switches to the REAL weak-scaling
measurement this repo was missing — a full stream-engine stencil step
(radius-1 mean6, the jacobi kernel) A/B'd between ``overlap=off`` and the
split-step schedule (ops/stream.py; docs/tuning.md "Stream overlap") under
the burst-aware protocol (alternate within one process, drop the post-idle
rep 0, steady-state median), with the bare exchange alternated in the same
rounds for the per-mesh exchange-ms figure.  The result is one
machine-readable JSON document (stdout line + ``--json PATH`` artifact):
per-mesh Mcells/s, exchange ms, and the split-vs-off delta — the per-mesh
rows of the weak-scaling story (scripts/run_weak_scaling.py sweeps meshes
[2,1,1] → [2,2,2] and collects one such artifact per shape).  ``--mesh
MX,MY,MZ`` forces the process grid on the first ``MX*MY*MZ`` devices and
weak-scales the per-chip base size per AXIS (512³/chip on [2,2,1] is a
1024×1024×512 global), so non-cubic meshes stay 512³/chip exactly.
Dryrun-capable: on a non-TPU backend the steps build in interpret mode and
the artifact records ``"dryrun": true`` — the schema is exercised
everywhere, the numbers mean something on hardware.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys

import jax
import jax.numpy as jnp

from stencil_tpu.bin import _common
from stencil_tpu.core.radius import Radius
from stencil_tpu.domain import DistributedDomain
from stencil_tpu.models.jacobi import weak_scaled_size
from stencil_tpu.utils.config import MethodFlags


def run(x: int, y: int, z: int, n_iters: int, args, name: str = "weak") -> str:
    def build_domain():
        dd = DistributedDomain(x, y, z)
        dd.set_methods(_common.parse_methods(args))
        dd.set_radius(Radius.constant(3))  # weak.cu:120
        dd.set_placement(_common.parse_strategy(args))
        _common.apply_exchange_route(args, dd)
        for i in range(4):  # weak.cu:132-135
            dd.add_data(f"d{i}", dtype=jnp.float32)
        dd.enable_exchange_stats(True)
        dd.realize()
        return dd

    dd = build_domain()
    if getattr(args, "tune", False):
        # the exchange-route axis gives weak/strong a search of their own
        # (PR 3 excluded them: nothing here consulted the tuner then).  The
        # winner persists for the workload; when it differs from the route
        # this realize resolved from a cold cache, re-realize so the
        # measured loop runs the tuned pick.
        from stencil_tpu.tune.runners import autotune_exchange

        report = autotune_exchange(dd)
        _common.tune_report_stderr(report)
        tuned_route = (report.config or {}).get("exchange_route")
        if tuned_route and tuned_route != dd.exchange_route():
            dd = build_domain()

    for _ in range(n_iters):
        dd.exchange()
        dd.swap()

    ranks, dev_count = _common.ranks_and_devcount()
    num_gpus = ranks * dev_count
    num_nodes = ranks
    s = dd.stats
    # Colocated/Peer/Direct byte columns are literal 0: those transports do
    # not exist on TPU — every byte rides the collective and is reported in
    # the MPI(B) column (the reference sums per-method counters,
    # src/stencil.cu:260-361)
    row = (
        f"{name},{_common.method_str(args)},{x},{y},{z},{x * y * z},"
        f"{dd.exchange_bytes_for_method(MethodFlags.CudaMpi)},"
        f"0,0,0,"
        f"{n_iters},{num_gpus},{num_nodes},{ranks},"
        f"{s.time_topo:e},{0.0:e},{0.0:e},{s.time_placement:e},"
        f"{s.time_realize:e},{s.time_plan:e},{s.time_create:e},"
        f"{s.time_exchange:e},{s.time_swap:e}"
    )
    return row


def _mean6_kernel(views, info):
    """The radius-1 jacobi stencil, written against the public kernel API —
    the overlap A/B's workload (the flagship kernel on the generic engine)."""
    out = {}
    for name, src in views.items():
        out[name] = (
            src.sh(-1, 0, 0)
            + src.sh(1, 0, 0)
            + src.sh(0, -1, 0)
            + src.sh(0, 1, 0)
            + src.sh(0, 0, -1)
            + src.sh(0, 0, 1)
        ) / 6.0
    return out


def _mean6_kernel_mxu(views, info):
    """``_mean6_kernel``'s declared axis-separable contraction form
    (PlaneView.plane_nbr_sum; ≤1 ulp/level) — lets the stream tuner's
    compute-unit A/B engage on this workload."""
    out = {}
    for name, src in views.items():
        out[name] = (
            src.sh(-1, 0, 0) + src.sh(1, 0, 0) + src.plane_nbr_sum()
        ) / 6.0
    return out


def parse_mesh(spec):
    """``"MX,MY,MZ"`` -> (mx, my, mz), or None."""
    if spec is None:
        return None
    parts = [int(v) for v in spec.split(",")]
    if len(parts) != 3 or any(v < 1 for v in parts):
        raise ValueError(f"--mesh wants MX,MY,MZ positive ints, got {spec!r}")
    return tuple(parts)


def overlap_domain_size(args, mesh, devices, weak_scale: bool):
    """Global extent for the overlap A/B.  Mesh mode weak-scales the
    per-chip base PER AXIS (512³/chip stays exact on non-cubic meshes);
    strong mode keeps the global size, rounded to the grid."""
    shell = max(args.halo_mult, 1)  # radius 1 x multiplier
    if mesh is not None:
        if weak_scale:
            return (args.x * mesh[0], args.y * mesh[1], args.z * mesh[2])
        return tuple(
            max(round(v / d), shell) * d
            for v, d in zip((args.x, args.y, args.z), mesh)
        )
    radius = Radius.constant(1)
    if weak_scale:
        n = len(devices)
        return _common.fit_to_mesh(
            weak_scaled_size(args.x, n),
            weak_scaled_size(args.y, n),
            weak_scaled_size(args.z, n),
            radius,
            devices=devices,
        )
    return _common.fit_to_mesh(args.x, args.y, args.z, radius, devices=devices)


def _hop_table(dd, s_exch: float) -> list:
    """The per-hop attribution table every per-mesh artifact carries: the
    ANALYTIC decomposition of the exchange bytes over each mesh hop
    (``DistributedDomain.exchange_hop_bytes``; hops on unsplit axes report
    0), with the measured per-exchange time apportioned by byte share.
    Tagged ``source: "analytic"`` — a profiler trace upgrades these to
    measured per-direction device time (``scripts/perf_report.py``)."""
    hop_bytes = dd.exchange_hop_bytes()
    total = sum(hop_bytes.values())
    return [
        {
            "axis": axis,
            "side": side,
            "bytes": nb,
            "share_of_bytes": round(nb / total, 4) if total else None,
            "est_ms": round(s_exch * 1e3 * nb / total, 6) if total else None,
            "source": "analytic",
        }
        for (axis, side), nb in sorted(hop_bytes.items())
    ]


def run_overlap(args, name: str = "weak", weak_scale: bool = True) -> dict:
    """The stream-engine overlap A/B at this mesh: build ``overlap=off`` and
    ``overlap=split`` steps over ONE realized domain (non-donating, the
    autotuner's trial pattern — the domain state never advances), alternate
    them with the bare exchange under the trial protocol, and return the
    per-mesh JSON document."""
    from stencil_tpu.tune.runners import _force_done
    from stencil_tpu.tune.trial import measure_alternating

    interpret = jax.default_backend() != "tpu"
    mesh = parse_mesh(args.mesh)
    devices = jax.devices()
    if mesh is not None:
        need = mesh[0] * mesh[1] * mesh[2]
        if need > len(devices):
            raise SystemExit(
                f"--mesh {args.mesh} needs {need} devices, have {len(devices)}"
            )
        devices = devices[:need]
    x, y, z = overlap_domain_size(args, mesh, devices, weak_scale)
    print(f"{name}-overlap domain: {x},{y},{z} on {len(devices)} chips",
          file=sys.stderr)

    dd = DistributedDomain(x, y, z)
    dd.set_radius(Radius.constant(1))
    dd.set_devices(devices)
    if mesh is not None:
        dd.set_partition(*mesh)
    dd.set_placement(_common.parse_strategy(args))
    if args.halo_mult > 1:
        dd.set_halo_multiplier(args.halo_mult)
    _common.apply_exchange_route(args, dd)
    hs = [dd.add_data(f"d{i}", dtype=jnp.float32) for i in range(args.quantities)]
    dd.realize()
    for i, h in enumerate(hs):
        dd.init_by_coords(h, lambda cx, cy, cz, i=i: jnp.sin(0.13 * (cx + 2 * cy + 3 * cz) + i))

    tune_section = None
    if getattr(args, "tune", False):
        # both new axes give weak/strong a tuner hook: the exchange route
        # (consulted by this realize's successor) and the stream plan incl.
        # overlap (consulted by auto-mode step builds)
        from stencil_tpu.tune.runners import autotune_exchange, autotune_stream

        ex_report = autotune_exchange(dd)
        _common.tune_report_stderr(ex_report)
        st_report = autotune_stream(
            dd, _mean6_kernel, x_radius=1, interpret=interpret,
            mxu_kernel=_mean6_kernel_mxu,
        )
        _common.tune_report_stderr(st_report)
        tune_section = {
            "exchange": ex_report.to_json(),
            "stream": st_report.to_json(),
        }

    steps = {}
    for ov in ("off", "split"):
        steps[ov] = dd.make_step(
            _mean6_kernel,
            engine="stream",
            donate=False,
            interpret=interpret,
            mxu_kernel=_mean6_kernel_mxu,
            stream_overlap=ov,
        )

    contracts_verified = None
    if getattr(args, "verify", False):
        # machine-check the property this A/B is about to measure: the
        # split step really is ppermute-independent in its interior, the
        # exchange really is the fused <=6-permute structure — a harness
        # that times a broken schedule produces a confidently wrong artifact
        from stencil_tpu import analysis
        from stencil_tpu.analysis.programs import tpu_shaped_trace

        with tpu_shaped_trace():  # verify the TPU-shaped lowering even on
            # a CPU dryrun (blend kernels on, as production traces them)
            arts = [
                analysis.step_artifact(
                    dd,
                    steps[ov],
                    label=f"{name}-overlap:{ov}",
                    axes={"overlap": ov, "exchange_route": dd.exchange_route()},
                )
                for ov in ("off", "split")
            ]
        findings = analysis.check_artifacts(arts)
        if findings:
            for f in findings:
                print(f.render(), file=sys.stderr)
            raise SystemExit(
                f"{len(findings)} program-contract finding(s) on the built "
                "steps — refusing to measure a schedule that is not what it "
                "claims (python -m stencil_tpu.analysis for the catalog)"
            )
        from stencil_tpu.analysis.framework import applied_contracts

        contracts_verified = applied_contracts(arts)

    def make_step_run(step):
        def go(ninner):
            out = step(dd._curr, ninner)
            _force_done(next(iter(out.values())))

        return go

    exch_fn = dd.make_exchange_route_fn(dd.exchange_route(), donate=False)

    from functools import partial

    from jax import lax

    @partial(jax.jit, static_argnums=1)
    def exch_many(arrays, s):
        return lax.fori_loop(0, s, lambda _, a: exch_fn(a), arrays)

    def exch_run(ninner):
        out = exch_many(dd._curr, ninner)
        _force_done(next(iter(out.values())))

    rt = _common.host_round_trip_s()
    runs = [make_step_run(steps["off"]), make_step_run(steps["split"]), exch_run]
    # the step twins share one dispatch size (same workload; calibrated on
    # off, split re-warmed at it), but the bare exchange is many times
    # cheaper and needs its OWN count — at the step's count its dispatch can
    # undershoot the host round trip and the rt subtraction goes negative
    # (the bench.py headline-vs-exchange sizing, measure_alternating's
    # per-run ``inner`` form)
    _, inner_step = _common.timed_inner_loop(runs[0], 2, rt, 1)
    runs[1](inner_step)
    _, inner_exch = _common.timed_inner_loop(exch_run, inner_step, rt, 1)
    rounds = measure_alternating(
        runs, [inner_step, inner_step, inner_exch], rt, args.ab_reps
    )
    s_off, s_split, s_exch = (statistics.median(r) for r in rounds)

    fabric_summary = None
    if getattr(args, "fabric_probe", False):
        # after the measured rounds: the probe's own dispatches must not
        # land inside the A/B timing.  Warm cache (same topology/chip/
        # payload under STENCIL_FABRIC_CACHE) = zero device work here.
        from stencil_tpu.telemetry import fabric as _fabric

        fdoc = _fabric.ensure(
            dd.mesh,
            nbytes=(1 << 16) if interpret else _fabric.DEFAULT_NBYTES,
        )
        fabric_summary = _fabric.summary(fdoc)

    cells = x * y * z
    dim = dd.placement.dim()
    doc = {
        "bench": f"{name}_overlap",
        "dryrun": interpret,
        "mesh": [dim.x, dim.y, dim.z],
        "chips": dd.num_subdomains(),
        "global": [x, y, z],
        "cells_per_chip": cells // dd.num_subdomains(),
        "quantities": args.quantities,
        "radius": 1,
        "halo_mult": args.halo_mult,
        "exchange_route": dd.exchange_route(),
        "plans": {
            ov: {
                k: steps[ov]._stream_plan.get(k)
                for k in ("route", "m", "z_slabs", "grouping", "overlap")
            }
            for ov in ("off", "split")
        },
        "measurement_protocol": {
            "alternating_within_process": True,
            "drop_rep0": True,
            "statistic": "median",
            "reps": args.ab_reps,
            "inner": {"step": inner_step, "exchange": inner_exch},
            "host_round_trip_s": rt,
        },
        "overlap": {
            ov: {
                "s_per_iter": s,
                "mcells_per_s": (cells / s / 1e6) if s > 0 else None,
                "mcells_per_s_per_chip": (
                    cells / s / 1e6 / dd.num_subdomains() if s > 0 else None
                ),
            }
            for ov, s in (("off", s_off), ("split", s_split))
        },
        "split_speedup": (s_off / s_split) if s_split > 0 else None,
        "exchange": {
            "s_per_exchange": s_exch,
            "ms_per_exchange": s_exch * 1e3,
            "bytes_per_exchange": dd.exchange_bytes_total(),
            "hops": _hop_table(dd, s_exch),
        },
    }
    if fabric_summary is not None:
        doc["fabric"] = fabric_summary
    if contracts_verified is not None:
        doc["contracts_verified"] = contracts_verified
    if tune_section is not None:
        doc["tune"] = tune_section
    return doc


def emit_overlap(doc, args) -> None:
    line = json.dumps(doc)
    if jax.process_index() != 0:
        return  # multi-host: one writer, or N processes race on the artifact
    print(line)
    if args.json:
        from stencil_tpu.utils.artifact import atomic_write_text

        atomic_write_text(args.json, line + "\n")


def build_parser(name: str, overlap_flags: bool = True) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(name)
    p.add_argument("x", type=int, nargs="?", default=512)
    p.add_argument("y", type=int, nargs="?", default=512)
    p.add_argument("z", type=int, nargs="?", default=512)
    p.add_argument("n_iters", type=int, nargs="?", default=30)
    p.add_argument("--kernel", action="store_true")
    p.add_argument("--peer", action="store_true")
    p.add_argument("--colo", action="store_true")
    p.add_argument("--naive", action="store_true", help="trivial placement (weak.cu --naive)")
    p.add_argument("--cuda-aware", dest="cuda_aware_mpi", action="store_true")
    p.add_argument("--staged", action="store_true")
    if not overlap_flags:
        # weak_exchange shares the base CSV parser but has no overlap A/B
        # and no tuner consult of its own — accepting --overlap/--tune there
        # would be a silent no-op, so the flags don't exist there at all
        _common.add_exchange_route_flag(p)
        _common.add_telemetry_flags(p)
        return p
    p.add_argument(
        "--overlap",
        action="store_true",
        help="run the stream-engine overlap A/B (off vs split-step) instead "
        "of the exchange-only CSV; emits one per-mesh JSON document "
        "(docs/tuning.md 'Stream overlap')",
    )
    p.add_argument(
        "--mesh",
        default=None,
        metavar="MX,MY,MZ",
        help="force the process grid on the first MX*MY*MZ devices; with "
        "--overlap the per-chip base size weak-scales per axis "
        "(512³/chip stays exact on non-cubic meshes)",
    )
    p.add_argument(
        "--json",
        default=None,
        metavar="PATH",
        help="with --overlap: also write the JSON document to PATH (the "
        "per-mesh weak-scaling artifact scripts/run_weak_scaling.py collects)",
    )
    p.add_argument(
        "--ab-reps",
        type=int,
        default=3,
        metavar="N",
        help="steady-state reps for the overlap A/B (alternating protocol, "
        "rep 0 dropped, median)",
    )
    p.add_argument(
        "--fabric-probe",
        action="store_true",
        help="with --overlap: probe (or warm-load from STENCIL_FABRIC_CACHE) "
        "the per-link fabric matrix for this mesh and embed its summary in "
        "the artifact (telemetry/fabric.py; docs/observability.md 'Fabric "
        "observatory')",
    )
    p.add_argument(
        "--verify",
        action="store_true",
        help="with --overlap: run the program-contract verifier "
        "(stencil_tpu.analysis) over the built off/split steps before "
        "timing them — abort instead of measuring a schedule that is not "
        "what it claims; the JSON doc records contracts_verified",
    )
    p.add_argument(
        "--halo-mult",
        type=int,
        default=2,
        metavar="K",
        help="halo multiplier for the overlap A/B domain (K*radius shells; "
        "K>=2 makes the wavefront route eligible)",
    )
    p.add_argument(
        "--quantities",
        type=int,
        default=1,
        metavar="N",
        help="fields exchanged/streamed in the overlap A/B",
    )
    # the exchange planner consults the tuned exchange-route config at
    # realize(); --exchange-route pins it per run, and --tune now runs the
    # exchange-route (and, with --overlap, stream-plan) searches here — the
    # overlap and route axes gave weak/strong planners of their own
    _common.add_exchange_route_flag(p)
    _common.add_tune_flags(p)
    _common.add_telemetry_flags(p)
    return p


def main(argv=None) -> int:
    args = build_parser("weak").parse_args(argv)
    args.trivial = args.naive
    _common.telemetry_begin(args)
    _common.tune_begin(args)
    try:
        if args.overlap:
            emit_overlap(run_overlap(args, name="weak", weak_scale=True), args)
            _common.telemetry_end(args)
            return 0
        devs = len(jax.devices())
        # weak.cu:63-65 round-to-nearest scaling
        x = weak_scaled_size(args.x, devs)
        y = weak_scaled_size(args.y, devs)
        z = weak_scaled_size(args.z, devs)
        x, y, z = _common.fit_to_mesh(x, y, z, Radius.constant(3))
        print(
            f"{devs} subdomains: {x},{y},{z}={x * y * z}",
            file=sys.stderr,
        )
        row = run(x, y, z, args.n_iters, args, name="weak")
        if jax.process_index() == 0:
            print(row)
        _common.telemetry_end(args)
        return 0
    finally:
        _common.tune_end(args)


if __name__ == "__main__":
    sys.exit(main())
