"""bench-qap — CRAFT (2-opt) vs exact QAP solver benchmark.

Parity target: reference bin/bench_qap.cu: for s = 2..39, generate
blkdiag / random / matched weight+distance matrices (bench_qap.cu:16-111) and
report per-solve seconds and solution cost for the 2-opt heuristic, plus the
exact solver for s < 9 (bench_qap.cu:112-160).  Output format matches:

    <name>
    size CRAFT(s) cost exact(s) cost
    2 <t> <c> <t> <c>
    ...
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from stencil_tpu.bin import _common
from stencil_tpu.parallel.qap import qap_solve, qap_solve_catch


def make_random(s: int, rng) -> tuple:
    return rng.random((s, s)) * 1e4, rng.random((s, s)) * 1e4


def make_matched(s: int, rng) -> tuple:
    w = rng.random((s, s)) * 1e4 + 1e-9
    return w, 1.0 / w


def blkdiag(s, d_min, d_max, od_min, od_max, blk_min, blk_max, rng) -> np.ndarray:
    """Block-diagonal high-weight blocks over a low-weight background
    (bench_qap.cu:50-96)."""
    m = np.zeros((s, s))
    r = 0
    while r < s:
        blk = min(int(rng.integers(blk_min, blk_max + 1)), s - r)
        m[r : r + blk, r : r + blk] = rng.uniform(d_min, d_max, (blk, blk))
        m[r : r + blk, r + blk :] = rng.uniform(od_min, od_max, (blk, s - r - blk))
        m[r + blk :, r : r + blk] = rng.uniform(od_min, od_max, (s - r - blk, blk))
        r += blk
    return m


def make_blkdiag(s: int, rng) -> tuple:
    # 2..26-sized blocks of high comm weight; 6x6 blocks of high bandwidth
    # (bench_qap.cu:98-110: a P9 NVLink-island-like distance structure)
    w = blkdiag(s, 100, 200, 10, 20, 2, 26, rng)
    d = blkdiag(s, 1 / 100.0, 1 / 64.0, 1 / 26.0, 1 / 25.0, 6, 6, rng)
    return w, d


def bench(name: str, func, n_iters: int, max_s: int, exact_below: int) -> None:
    print(name)
    print("size CRAFT(s) cost exact(s) cost")
    rng = np.random.default_rng(0)
    for s in range(2, max_s):
        w, d = func(s, rng)
        t0 = time.perf_counter()
        for _ in range(n_iters):
            _, cost = qap_solve_catch(w, d)
        craft_t = (time.perf_counter() - t0) / n_iters
        line = f"{s} {craft_t:g} {cost:g}"
        if s < exact_below:
            t0 = time.perf_counter()
            for _ in range(n_iters):
                _, cost = qap_solve(w, d)
            exact_t = (time.perf_counter() - t0) / n_iters
            line += f" {exact_t:g} {cost:g}"
        else:
            line += " - -"
        print(line)


def main(argv=None) -> int:
    p = argparse.ArgumentParser("bench-qap")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--max-size", type=int, default=40)
    p.add_argument("--exact-below", type=int, default=9)
    _common.add_telemetry_flags(p)
    args = p.parse_args(argv)
    _common.telemetry_begin(args)
    bench("blkdiag", make_blkdiag, args.iters, args.max_size, args.exact_below)
    bench("random", make_random, args.iters, args.max_size, args.exact_below)
    bench("matched", make_matched, args.iters, args.max_size, args.exact_below)
    _common.telemetry_end(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
