"""ICI/DCN exchange cost model — quantify multi-chip viability on paper.

One real chip is all this environment ever sees, so the wavefront macro's
cross-chip critical path cannot be *measured* here; this model puts a number
on it instead: per-axis sweep bytes (``core/geometry.sweep_bytes`` pieces) /
measured-or-default edge bandwidth + a per-collective latency, classified
ICI vs DCN by whether the mesh neighbors along the axis live in different
processes.  ``DistributedDomain.write_plan`` appends the projection so every
plan dump (the reference's ``plan_<rank>.txt``, src/stencil.cu:259-353 +
``exchange_bytes_for_method``) carries projected ms/exchange per direction.

Defaults are v5e datasheet-class figures; refine them with THIS framework's
own measurements: ``LinkModel.from_pingpong`` ingests a pingpong round trip
(bin/pingpong.py), and bench-alltoallv's contended matrix traversals bound
the congestion factor.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

#: v5e class defaults: ~45 GB/s usable per ICI link direction (4x 400 Gbps
#: links, counting one link per mesh-axis direction), ~6 GB/s per host NIC
#: for DCN hops, ~25 us per collective dispatch.  Deliberately conservative;
#: measurements override.
ICI_DEFAULT_GBPS = 45.0
DCN_DEFAULT_GBPS = 6.0
LATENCY_DEFAULT_US = 25.0


@dataclasses.dataclass
class LinkModel:
    ici_gbps: float = ICI_DEFAULT_GBPS
    dcn_gbps: float = DCN_DEFAULT_GBPS
    latency_us: float = LATENCY_DEFAULT_US

    @classmethod
    def from_pingpong(cls, nbytes: int, round_trip_s: float, **kw) -> "LinkModel":
        """Edge bandwidth from one pingpong row (bin/pingpong.py): a round
        trip moves ``nbytes`` each way, so bw = 2*nbytes/time.  Extra kwargs
        override the other fields."""
        gbps = 2.0 * nbytes / max(round_trip_s, 1e-12) / 1e9
        return cls(ici_gbps=gbps, **kw)

    def gbps(self, kind: str) -> float:
        return self.ici_gbps if kind == "ici" else self.dcn_gbps


def axis_edge_kinds(mesh) -> List[str]:
    """Classify each mesh axis's neighbor edges: "self" for unsharded axes
    (self-permute, no wire), "dcn" if ANY adjacent pair along the axis —
    including the periodic wrap edge — crosses a process boundary (the
    collective's critical hop rides the slowest link), "ici" otherwise.
    A node-major axis mixing intra- and inter-host hops is therefore
    priced at DCN speed.

    EVERY line along the axis is scanned (all index combinations of the
    other axes), not just the lead line: a mesh whose process boundaries are
    not axis-aligned planes (e.g. a snaking device order) would otherwise be
    misclassified as ici and under-project the cost in ``write_plan``.
    Device counts are small, so the exhaustive scan is cheap."""
    import itertools

    import numpy as np

    devs = np.asarray(mesh.devices)
    proc = np.vectorize(lambda d: getattr(d, "process_index", 0))(devs)
    kinds = []
    for ax in range(devs.ndim):
        size = devs.shape[ax]
        if size == 1:
            kinds.append("self")
            continue
        other_dims = [range(devs.shape[b]) for b in range(devs.ndim) if b != ax]
        kind = "ici"
        for rest in itertools.product(*other_dims):
            for j in range(size):
                a_idx = list(rest[:ax]) + [j] + list(rest[ax:])
                b_idx = list(a_idx)
                b_idx[ax] = (j + 1) % size
                if proc[tuple(a_idx)] != proc[tuple(b_idx)]:
                    kind = "dcn"
                    break
            if kind == "dcn":
                break
        kinds.append(kind)
    return kinds


def projected_exchange_cost(
    spec,
    itemsizes: Sequence[int],
    kinds: Sequence[str],
    link: LinkModel = None,
) -> Tuple[List[Tuple[str, int, str, float]], float]:
    """Project one 3-axis-sweep exchange on the given edge kinds.

    Returns ``(rows, total_ms)`` where each row is
    ``(axis_dir_label, bytes, edge_kind, ms)`` for the six sweep messages
    (each axis's slab spans the full raw extent of the other axes — the
    ``sweep_bytes`` accounting, core/geometry.py:200).  The lo/hi pair of an
    axis rides the same links in opposite directions (full duplex), so the
    axis cost is max(lo, hi) + latency; axes serialize (the sweep order is a
    data dependency: later axes carry earlier axes' halos).  A "self" edge
    (unsharded axis) costs one HBM-side copy, modeled at ICI speed — cheap
    and never the critical path.
    """
    link = link or LinkModel()
    raw = spec.raw_size()
    r = spec.radius
    itemsize_sum = sum(int(s) for s in itemsizes)
    rows: List[Tuple[str, int, str, float]] = []
    total_ms = 0.0
    for ax, name in enumerate("xyz"):
        widths = (r.axis(ax, -1), r.axis(ax, +1))
        if widths == (0, 0):
            continue
        others = [raw[b] for b in range(3) if b != ax]
        plane = others[0] * others[1]
        kind = kinds[ax]
        gbps = link.gbps("ici" if kind == "self" else kind)
        pair_ms = []
        for w, dlabel in zip(widths, ("-", "+")):
            nbytes = itemsize_sum * plane * w
            ms = nbytes / (gbps * 1e9) * 1e3
            rows.append((f"{dlabel}{name}", nbytes, kind, ms))
            pair_ms.append(ms)
        total_ms += max(pair_ms) + link.latency_us / 1e3
    return rows, total_ms


def format_cost_report(rows, total_ms, link: LinkModel, halo_mult: int = 1) -> List[str]:
    """Plan-dump lines for ``write_plan``."""
    lines = [
        "",
        "# projected exchange cost (ICI/DCN model, parallel/cost.py: "
        f"ici={link.ici_gbps:.1f} GB/s dcn={link.dcn_gbps:.1f} GB/s "
        f"latency={link.latency_us:.0f} us; lo/hi full duplex, axes serialize)",
    ]
    for label, nbytes, kind, ms in rows:
        lines.append(f"dir={label} bytes={nbytes} edge={kind} projected_ms={ms:.4f}")
    lines.append(f"# projected ms per exchange: {total_ms:.4f}")
    if halo_mult > 1:
        lines.append(
            f"# projected ms per MACRO step (halo multiplier {halo_mult}: one "
            f"exchange per {halo_mult} iterations): {total_ms:.4f} "
            f"({total_ms / halo_mult:.4f} amortized per iteration)"
        )
    return lines
