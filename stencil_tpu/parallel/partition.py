"""3D domain decomposition by recursive prime-factor splitting.

Parity targets: ``RankPartition`` (reference include/stencil/partition.hpp:23-146)
and ``NodePartition`` (partition.hpp:148-310).

* ``RankPartition(size, n)``: split ``size`` into ``n`` subdomains by the
  prime factors of ``n``, largest factor first, always cutting the currently
  longest axis (x wins ties, then y) — partition.hpp:56-78.
* ``NodePartition(size, radius, nodes, gpus)``: same recursion but each step
  cuts the plane with the smallest radius-weighted interface area
  ``size.y*size.z*(r+x + r-x)`` etc. (partition.hpp:220-238), applied twice:
  across nodes, then across GPUs within a node (partition.hpp:213-261).  On
  TPU the two levels map to DCN-slice x ICI-mesh.
* Uneven remainders: subdomain sizes are ``ceil`` sizes with trailing indices
  shrunk by 1 (``subdomain_size`` partition.hpp:83-98, ``subdomain_origin``
  partition.hpp:100-114).
* ``linearize``/``dimensionize``: x fastest (partition.hpp:117-143).

TPU note: XLA shards must be equal-sized, so ``DistributedDomain`` uses the
*even* case directly and handles remainders by padding the global array up to
``dim * ceil_size`` with a validity mask; this module still reproduces the
reference's uneven sizes/origins exactly because they define the coordinate
system of the unpadded user domain (and the parity tests).
"""

from __future__ import annotations

from typing import List

from stencil_tpu.core.dim3 import Dim3
from stencil_tpu.core.radius import Radius


def prime_factors(n: int) -> List[int]:
    """Prime factors of ``n``, largest first (partition.hpp:31-50: the
    comparator sorts descending)."""
    result: List[int] = []
    if n == 0:
        return result
    while n % 2 == 0:
        result.append(2)
        n //= 2
    i = 3
    while i * i <= n:
        while n % i == 0:
            result.append(i)
            n //= i
        i += 2
    if n > 2:
        result.append(n)
    return sorted(result, reverse=True)


def _div_ceil(n: int, d: int) -> int:
    return (n + d - 1) // d


class _PartitionBase:
    """Shared uneven-remainder and index math."""

    _size: Dim3  # ceil subdomain size
    _rem: Dim3  # input size % dim

    def dim(self) -> Dim3:
        raise NotImplementedError

    def subdomain_size(self, idx) -> Dim3:
        """partition.hpp:83-98: trailing indices shrink by one on axes with a
        remainder."""
        idx = Dim3.of(idx)
        ret = [self._size.x, self._size.y, self._size.z]
        for ax in range(3):
            if self._rem[ax] != 0 and idx[ax] >= self._rem[ax]:
                ret[ax] -= 1
        return Dim3(*ret)

    def subdomain_origin(self, idx) -> Dim3:
        """partition.hpp:100-114."""
        idx = Dim3.of(idx)
        ret = [self._size.x * idx.x, self._size.y * idx.y, self._size.z * idx.z]
        for ax in range(3):
            if self._rem[ax] != 0 and idx[ax] >= self._rem[ax]:
                ret[ax] -= idx[ax] - self._rem[ax]
        return Dim3(*ret)

    def linearize(self, idx) -> int:
        """x fastest (partition.hpp:117-130)."""
        idx = Dim3.of(idx)
        d = self.dim()
        assert idx.all_ge(0) and idx.x < d.x and idx.y < d.y and idx.z < d.z
        return idx.x + idx.y * d.x + idx.z * d.y * d.x

    def dimensionize(self, i: int) -> Dim3:
        """partition.hpp:133-143."""
        d = self.dim()
        assert 0 <= i < d.flatten()
        x = i % d.x
        i //= d.x
        y = i % d.y
        z = i // d.y
        return Dim3(x, y, z)

    def is_even(self) -> bool:
        return self._rem == Dim3(0, 0, 0)


class RankPartition(_PartitionBase):
    """Longest-axis recursive splitter (partition.hpp:56-78)."""

    def __init__(self, size, n: int):
        size = Dim3.of(size)
        self._dim = Dim3(1, 1, 1)
        cur = size
        for amt in prime_factors(n):
            if amt < 2:
                continue
            if cur.x >= cur.y and cur.x >= cur.z:
                cur = cur.replace(0, _div_ceil(cur.x, amt))
                self._dim = self._dim.replace(0, self._dim.x * amt)
            elif cur.y >= cur.z:
                cur = cur.replace(1, _div_ceil(cur.y, amt))
                self._dim = self._dim.replace(1, self._dim.y * amt)
            else:
                cur = cur.replace(2, _div_ceil(cur.z, amt))
                self._dim = self._dim.replace(2, self._dim.z * amt)
        self._size = cur
        self._rem = size % self._dim

    def dim(self) -> Dim3:
        return self._dim


class ManualPartition(_PartitionBase):
    """User-specified process grid (the reference's future-work "manual
    partition", README.md:157-176): the mesh shape is taken verbatim instead
    of derived by the splitters."""

    def __init__(self, size, dim):
        size = Dim3.of(size)
        self._dim = Dim3.of(dim)
        assert self._dim.all_ge(1)
        self._size = Dim3(
            _div_ceil(size.x, self._dim.x),
            _div_ceil(size.y, self._dim.y),
            _div_ceil(size.z, self._dim.z),
        )
        self._rem = size % self._dim

    def dim(self) -> Dim3:
        return self._dim

    def idx(self, i: int) -> Dim3:
        return self.dimensionize(i)


class NodePartition(_PartitionBase):
    """Two-level min-interface splitter (partition.hpp:210-264).

    ``sys_dim`` is the across-node (DCN) grid, ``node_dim`` the within-node
    (ICI) grid; total grid is their product.
    """

    def __init__(self, size, radius: Radius, nodes: int, gpus: int):
        size = Dim3.of(size)
        self._sys_dim = Dim3(1, 1, 1)
        self._node_dim = Dim3(1, 1, 1)
        cur = size

        def min_interface_axis(c: Dim3) -> int:
            # partition.hpp:227-231: interface area scaled by the summed
            # +/- face radii of the cut axis; x wins ties, then y
            x_iface = c.y * c.z * (radius.dir(1, 0, 0) + radius.dir(-1, 0, 0))
            y_iface = c.x * c.z * (radius.dir(0, 1, 0) + radius.dir(0, -1, 0))
            z_iface = c.x * c.y * (radius.dir(0, 0, 1) + radius.dir(0, 0, -1))
            if x_iface <= y_iface and x_iface <= z_iface:
                return 0
            if y_iface <= z_iface:
                return 1
            return 2

        for level in range(2):
            dim = Dim3(1, 1, 1)
            for amt in prime_factors(nodes if level == 0 else gpus):
                if amt < 2:
                    continue
                ax = min_interface_axis(cur)
                cur = cur.replace(ax, _div_ceil(cur[ax], amt))
                dim = dim.replace(ax, dim[ax] * amt)
            if level == 0:
                self._sys_dim = dim
            else:
                self._node_dim = dim

        self._size = cur
        self._rem = size % (self._sys_dim * self._node_dim)

    def sys_dim(self) -> Dim3:
        return self._sys_dim

    def node_dim(self) -> Dim3:
        return self._node_dim

    def dim(self) -> Dim3:
        return self._sys_dim * self._node_dim

    def sys_idx(self, i: int) -> Dim3:
        return _dimensionize_in(i, self._sys_dim)

    def node_idx(self, i: int) -> Dim3:
        return _dimensionize_in(i, self._node_dim)

    def idx(self, i: int) -> Dim3:
        return _dimensionize_in(i, self.dim())


def _dimensionize_in(i: int, dim: Dim3) -> Dim3:
    assert 0 <= i < dim.flatten()
    x = i % dim.x
    i //= dim.x
    y = i % dim.y
    z = i // dim.y
    return Dim3(x, y, z)
