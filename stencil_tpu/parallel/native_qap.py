"""ctypes bindings for the native QAP solvers (native/qap.cpp).

Loads ``libstencil_native.so``, building it with ``make`` on first use when a
toolchain is available.  Importing this module raises ImportError when the
library can neither be found nor built — ``qap.solve_auto`` catches that and
falls back to the pure-Python solvers.  Set ``STENCIL_NATIVE=0`` to force the
fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Tuple

import numpy as np

from stencil_tpu.utils.config import env_bool

try:
    _native_enabled = env_bool("STENCIL_NATIVE", True)
except ValueError as e:
    # module-import-time read, lazily triggered from qap.solve_auto whose
    # fallback guard catches ImportError/OSError only: a malformed value
    # must warn-and-default (the STENCIL_OUTPUT_LEVEL convention), not
    # abort placement planning with an escaping ValueError
    from stencil_tpu.utils.logging import log_warn

    log_warn(f"{e}; treating STENCIL_NATIVE as enabled")
    _native_enabled = True
if not _native_enabled:
    raise ImportError("native disabled via STENCIL_NATIVE=0")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libstencil_native.so")


def _load() -> ctypes.CDLL:
    if not os.path.exists(_LIB_PATH):
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except (OSError, subprocess.SubprocessError) as e:
            raise ImportError(f"cannot build native library: {e}") from e
    return ctypes.CDLL(_LIB_PATH)


_lib = _load()

_DP = ctypes.POINTER(ctypes.c_double)
_IP = ctypes.POINTER(ctypes.c_int)
for name in ("stencil_qap_solve", "stencil_qap_solve_catch"):
    fn = getattr(_lib, name)
    fn.argtypes = [_DP, _DP, ctypes.c_int, _IP]
    fn.restype = ctypes.c_double
_lib.stencil_qap_cost.argtypes = [_DP, _DP, _IP, ctypes.c_int]
_lib.stencil_qap_cost.restype = ctypes.c_double


def _as_c(m: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(m, dtype=np.float64))


def qap_cost(w: np.ndarray, d: np.ndarray, f) -> float:
    w, d = _as_c(w), _as_c(d)
    fa = np.ascontiguousarray(np.asarray(f, dtype=np.int32))
    return float(
        _lib.stencil_qap_cost(
            w.ctypes.data_as(_DP), d.ctypes.data_as(_DP), fa.ctypes.data_as(_IP), w.shape[0]
        )
    )


def _solve(fn, w: np.ndarray, d: np.ndarray) -> Tuple[List[int], float]:
    w, d = _as_c(w), _as_c(d)
    n = w.shape[0]
    assert w.shape == (n, n) and d.shape == (n, n), (w.shape, d.shape)
    out = np.zeros(n, dtype=np.int32)
    c = fn(w.ctypes.data_as(_DP), d.ctypes.data_as(_DP), n, out.ctypes.data_as(_IP))
    return out.tolist(), float(c)


def qap_solve(w: np.ndarray, d: np.ndarray) -> Tuple[List[int], float]:
    return _solve(_lib.stencil_qap_solve, w, d)


def qap_solve_catch(w: np.ndarray, d: np.ndarray) -> Tuple[List[int], float]:
    return _solve(_lib.stencil_qap_solve_catch, w, d)


def solve_auto(w: np.ndarray, d: np.ndarray, exact_limit: int = 8) -> Tuple[List[int], float]:
    n = np.asarray(w).shape[0]
    if n <= exact_limit:
        return qap_solve(w, d)
    return qap_solve_catch(w, d)
