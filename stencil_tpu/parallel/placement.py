"""Topology-aware placement of subdomains onto devices.

Parity target: ``Placement`` / ``Trivial`` / ``NodeAware`` (reference
include/stencil/partition.hpp:314-864).  The reference assigns subdomains to
GPUs by solving a QAP between a stencil communication matrix (halo sizes,
periodic wrap — partition.hpp:770-799) and an NVML bandwidth-derived distance
matrix (partition.hpp:752-767, 802-803).  Here the distance matrix comes from
ICI torus hop counts (``topology.distance_matrix``), the comm matrix math is
identical, and the solved permutation orders the device grid handed to
``jax.sharding.Mesh`` — placing neighboring subdomains on neighboring chips so
halo ppermutes ride single ICI hops.

A third strategy, ``MeshUtils``, delegates to
``jax.experimental.mesh_utils.create_device_mesh`` (XLA's own torus-aware
arranger) — the recommended default on real pods; ``NodeAware`` is the
reference-parity path and the only one that handles arbitrary comm matrices.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from stencil_tpu.core.dim3 import Dim3
from stencil_tpu.core.geometry import halo_extent
from stencil_tpu.core.radius import Radius
from stencil_tpu.parallel import topology
from stencil_tpu.parallel.partition import NodePartition
from stencil_tpu.parallel.qap import qap_cost, solve_auto
from stencil_tpu.utils.config import PlacementStrategy


def comm_matrix(partition: NodePartition, radius: Radius) -> np.ndarray:
    """Subdomain-to-subdomain communication weights (partition.hpp:770-799):
    ``W[i][j]`` = points sent i->j, i.e. the halo extent of the neighbor
    direction, 0 for non-neighbors; periodic wrap across the global grid."""
    dim = partition.dim()
    n = dim.flatten()
    w = np.zeros((n, n), dtype=float)
    for i in range(n):
        src = partition.idx(i)
        for j in range(n):
            dst = partition.idx(j)
            d = dst - src
            # periodic boundary (partition.hpp:777-790)
            vals = []
            for ax in range(3):
                v = d[ax]
                if v != 0 and v == dim[ax] - 1:
                    v = -1
                if v != 0 and v == 1 - dim[ax]:
                    v = 1
                vals.append(v)
            d = Dim3(*vals)
            if d == Dim3(0, 0, 0) or d.any_gt(1) or d.any_lt(-1):
                continue
            sz = partition.subdomain_size(src)
            w[i, j] = float(halo_extent(d, sz, radius).flatten())
    return w


class Placement:
    """Maps partition indices <-> devices; wraps the solved assignment.

    ``assignment[i]`` = device slot for subdomain with linear index ``i``
    (reference ``components`` vector, partition.hpp:803-835).
    """

    def __init__(self, partition: NodePartition, devices: Sequence, assignment: List[int], cost: float = float("nan")):
        self.partition = partition
        self.devices = list(devices)
        self.assignment = list(assignment)
        self.cost = cost
        n = partition.dim().flatten()
        assert len(self.assignment) == n == len(self.devices), (n, len(self.devices))
        self._idx_of_device = {id(self.devices[dev]): i for i, dev in enumerate(self.assignment)}

    # --- reference Placement interface (partition.hpp:314-337) ---------------
    def dim(self) -> Dim3:
        return self.partition.dim()

    def get_device(self, idx) -> object:
        """Device hosting subdomain ``idx`` (analog of get_cuda, 327)."""
        return self.devices[self.assignment[self.partition.linearize(idx)]]

    def get_idx(self, device) -> Dim3:
        """Subdomain hosted by ``device`` (analog of get_idx, 318)."""
        return self.partition.idx(self._idx_of_device[id(device)])

    def subdomain_size(self, idx) -> Dim3:
        return self.partition.subdomain_size(idx)

    def subdomain_origin(self, idx) -> Dim3:
        return self.partition.subdomain_origin(idx)

    # --- mesh construction ----------------------------------------------------
    def device_grid(self) -> np.ndarray:
        """(px, py, pz) object array of devices for ``jax.sharding.Mesh``."""
        dim = self.dim()
        grid = np.empty((dim.x, dim.y, dim.z), dtype=object)
        for i in range(dim.flatten()):
            idx = self.partition.idx(i)
            grid[idx.x, idx.y, idx.z] = self.devices[self.assignment[i]]
        return grid

    def report(self) -> str:
        """Placement report — the analog of the reference's plan_<rank>.txt
        dump (src/stencil.cu:266-353)."""
        lines = [f"# placement: dim={self.dim()} cost={self.cost}"]
        for i in range(self.dim().flatten()):
            idx = self.partition.idx(i)
            dev = self.devices[self.assignment[i]]
            coords = topology.device_coords(dev)
            lines.append(
                f"subdomain {idx} size={self.subdomain_size(idx)} "
                f"origin={self.subdomain_origin(idx)} -> device {dev.id}"
                + (f" coords={coords}" if coords else "")
            )
        return "\n".join(lines)


class TrivialPlacement(Placement):
    """Round-robin, no topology (partition.hpp:339-493)."""

    def __init__(self, partition: NodePartition, devices: Sequence):
        n = partition.dim().flatten()
        super().__init__(partition, devices, list(range(n)))


class NodeAwarePlacement(Placement):
    """QAP of comm matrix vs torus distance (partition.hpp:573-864)."""

    def __init__(self, partition: NodePartition, devices: Sequence, radius: Radius):
        w = comm_matrix(partition, radius)
        dist = topology.distance_matrix(devices)
        assignment, cost = solve_auto(w, dist)
        super().__init__(partition, devices, assignment, cost)


def make_placement(
    strategy: PlacementStrategy,
    partition: NodePartition,
    devices: Sequence,
    radius: Radius,
) -> Placement:
    if strategy == PlacementStrategy.Trivial:
        return TrivialPlacement(partition, devices)
    return NodeAwarePlacement(partition, devices, radius)
