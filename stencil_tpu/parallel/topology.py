"""Device/torus topology introspection.

Parity target: the discovery layer L1 — ``MpiTopology`` (reference
include/stencil/mpi_topology.hpp:7) and ``gpu_topo::bandwidth`` (NVML distance
matrix, src/gpu_topology.cpp:95-139).  On TPU the fabric is the ICI torus:
``jax.Device.coords`` gives chip coordinates, and hop distance replaces the
NVML common-ancestor tiers.  ``bandwidth = 1 / distance`` exactly as the
reference (gpu_topology.cpp:95).

For CPU (test) devices without coords, distance degrades to linear index
distance — the moral equivalent of the reference degrading when NVML is
absent.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

#: distance between a device and itself (gpu_topology.cpp:20-27 tier SAME=0.1,
#: so self-bandwidth is large but finite)
_SELF_DISTANCE = 0.1


def device_coords(dev) -> Optional[Tuple[int, ...]]:
    """TPU chips expose .coords (an (x,y,z) torus position); CPU devices don't."""
    c = getattr(dev, "coords", None)
    if c is None:
        return None
    return tuple(int(v) for v in c)


def torus_dims(devices: Sequence) -> Optional[Tuple[int, ...]]:
    coords = [device_coords(d) for d in devices]
    if any(c is None for c in coords):
        return None
    arr = np.array(coords)
    return tuple(int(v) for v in arr.max(axis=0) + 1)


def distance_matrix(devices: Sequence) -> np.ndarray:
    """Pairwise hop distance: torus Manhattan distance (with wrap) when chip
    coords exist, else linear index distance.  Devices on different processes
    (DCN) get an extra penalty, mirroring the reference's inter-node tier
    being the most distant (gpu_topology.cpp:72-87)."""
    n = len(devices)
    dims = torus_dims(devices)
    dist = np.zeros((n, n), dtype=float)
    for i in range(n):
        for j in range(n):
            if i == j:
                dist[i, j] = _SELF_DISTANCE
                continue
            if dims is not None:
                ci = np.array(device_coords(devices[i]))
                cj = np.array(device_coords(devices[j]))
                d = np.abs(ci - cj)
                d = np.minimum(d, np.array(dims) - d)  # torus wrap
                hops = float(d.sum())
            else:
                hops = float(abs(i - j))
            if devices[i].process_index != devices[j].process_index:
                hops += 16.0  # DCN crossing dominates ICI hops
            dist[i, j] = max(hops, _SELF_DISTANCE)
    return dist


def bandwidth_matrix(devices: Sequence) -> np.ndarray:
    """gpu_topology.cpp:95: bandwidth = 1 / distance."""
    return 1.0 / distance_matrix(devices)


def num_processes(devices: Sequence) -> int:
    return len({d.process_index for d in devices})


def devices_by_process(devices: Sequence) -> List[List]:
    by: dict = {}
    for d in devices:
        by.setdefault(d.process_index, []).append(d)
    return [by[k] for k in sorted(by)]
