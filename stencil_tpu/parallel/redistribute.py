"""On-device array redistribution: mesh A -> mesh B without a full gather.

The elastic-restore path (io/checkpoint.py) moves a domain between meshes
through a disk round trip: gather interiors to host, re-scatter onto the
new mesh.  This module is the IN-MEMORY generalization of that re-scatter
("Memory-efficient array redistribution through portable collective
communication", PAPERS.md arxiv 2112.01075): the sharded interior state
moves from the source mesh to the target mesh as a SCHEDULE of portable
collectives — one ``lax.ppermute`` of a bounded staging buffer per round —
with peak per-chip memory bounded by a constant number of shard-sized
buffers.  No chip ever materializes more than its own source block, its
own target block, and the round's staging chunks.

The schedule, planned entirely on host (``plan_redistribution``):

1. Both partitions are padded equal splits with a last-shard remainder
   (``DistributedDomain.realize``'s rule), so the intersection of any
   source shard's VALID interior with any target shard's is one global
   rectangle — the **chunk** that must travel from source chip i to
   target chip j.
2. Chunks are grouped into **rounds** where every chip appears at most
   once as a sender and once as a receiver — each round is one permutation,
   i.e. one ``ppermute`` over the 1-D **union mesh** (source ∪ target
   devices).  Chips without a chunk in a round run the same program on
   garbage and mask it away (SPMD uniformity).
3. Within a round all chunks pad to the round's elementwise-max shape (the
   **staging buffer**, never larger than a shard); per-rank offset tables
   drive the slicing, the in-buffer alignment roll, and the receiver's
   masked blend — all traced through ``lax.axis_index`` lookups so the
   program is one jaxpr for every rank.

The traced program is machine-checked by the ``redistribute-bounded``
program contract (stencil_tpu/analysis): every intermediate inside the
shard-mapped body stays under a constant multiple of the shard size, and
no gathering collective appears anywhere.

The result is bitwise-identical to checkpoint-elastic-restore: target
blocks are zero-initialized and only valid interiors are written — exactly
``set_quantity``'s scatter — and values move at the STORED dtype (bf16
storage included), so not a single ulp is touched in flight.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from stencil_tpu.core.dim3 import Dim3

#: the staging-memory bound the redistribute-bounded contract enforces:
#: no intermediate in the shard-mapped body may exceed this many times the
#: larger of the source/target block sizes (the alignment roll's concat
#: doubles one staging buffer; everything else is <= one block)
STAGING_BOUND_FACTOR = 3

#: the 1-D union-mesh axis every redistribution ppermute rides
UNION_AXIS = "r"


class ReshardImpossibleError(ValueError):
    """The requested target mesh cannot receive this domain (no admissible
    partition, shard smaller than the shell, source buffers already
    consumed/gone).  The supervisor answers with the checkpoint-elastic-
    restore fallback; direct callers see a pointed error."""


@dataclasses.dataclass(frozen=True)
class SideGeometry:
    """One side of a redistribution: the padded-equal-split facts that
    place every shard's valid interior in global coordinates."""

    dim: Tuple[int, int, int]  # mesh extent per axis
    n: Tuple[int, int, int]  # per-shard interior (padded equal split)
    raw: Tuple[int, int, int]  # allocated shard extent (interior + shell)
    lo: Tuple[int, int, int]  # shell offset of the interior in the block
    valid_last: Tuple[Optional[int], Optional[int], Optional[int]]
    devices: Tuple  # flattened device grid, C order over (x, y, z)

    @classmethod
    def of_domain(cls, dd) -> "SideGeometry":
        dim = dd.placement.dim()
        raw = dd.local_spec().raw_size()
        lo = dd._shell_radius.lo()
        return cls(
            dim=(dim.x, dim.y, dim.z),
            n=tuple(dd.local_spec().sz),
            raw=(raw.x, raw.y, raw.z),
            lo=(lo.x, lo.y, lo.z),
            valid_last=tuple(dd._valid_last),
            devices=tuple(dd.mesh.devices.flat),
        )

    def n_shards(self) -> int:
        return self.dim[0] * self.dim[1] * self.dim[2]

    def shard_index(self, flat: int) -> Tuple[int, int, int]:
        dx, dy, dz = self.dim
        return (flat // (dy * dz), (flat // dz) % dy, flat % dz)

    def valid(self, idx: Tuple[int, int, int]) -> Tuple[int, int, int]:
        return tuple(
            self.valid_last[a]
            if (idx[a] == self.dim[a] - 1 and self.valid_last[a] is not None)
            else self.n[a]
            for a in range(3)
        )


@dataclasses.dataclass(frozen=True)
class ChunkMove:
    """One rectangle travelling from source shard to target shard, in
    block-local coordinates on both ends."""

    src_rank: int  # union-mesh rank holding the source shard
    dst_rank: int  # union-mesh rank holding the target shard
    src_off: Tuple[int, int, int]  # offset inside the source block
    dst_off: Tuple[int, int, int]  # offset inside the target block
    size: Tuple[int, int, int]


@dataclasses.dataclass(frozen=True)
class Round:
    """One permutation round: a staging shape plus per-rank host tables
    (rows indexed by union rank; non-participants carry zero rows and a
    zero mask extent, so every rank runs the same traced program)."""

    staging: Tuple[int, int, int]
    pairs: Tuple[Tuple[int, int], ...]  # ppermute (src, dst) routing
    send_start: np.ndarray  # (R, 3) clamped dynamic_slice starts
    send_shift: np.ndarray  # (R, 3) in-buffer alignment roll
    recv_start: np.ndarray  # (R, 3) clamped write-window starts
    recv_pos: np.ndarray  # (R, 3) valid-data offset inside the window
    recv_size: np.ndarray  # (R, 3) valid extent (zeros = not a receiver)


@dataclasses.dataclass(frozen=True)
class RedistributionPlan:
    """The full host-side schedule for one (size, mesh A, mesh B) move."""

    size: Tuple[int, int, int]
    src: SideGeometry
    dst: SideGeometry
    union_devices: Tuple  # source ∪ target devices, source order first
    src_rank: Dict[int, int]  # source flat shard -> union rank
    dst_rank: Dict[int, int]  # target flat shard -> union rank
    rounds: Tuple[Round, ...]

    def moved_cells(self) -> int:
        return int(np.prod(self.size))

    def bound_bytes(self, itemsize: int, cell_count: int = 1) -> int:
        """The per-chip staging bound the contract enforces for a quantity
        of this itemsize: STAGING_BOUND_FACTOR x the larger block."""
        blk = max(int(np.prod(self.src.raw)), int(np.prod(self.dst.raw)))
        return STAGING_BOUND_FACTOR * blk * cell_count * itemsize


def _chunks(src: SideGeometry, dst: SideGeometry,
            src_rank: Dict[int, int], dst_rank: Dict[int, int]) -> List[ChunkMove]:
    """Every (source shard ∩ target shard) valid-interior rectangle."""
    out: List[ChunkMove] = []
    for jf in range(dst.n_shards()):
        jidx = dst.shard_index(jf)
        jv = dst.valid(jidx)
        jlo = [jidx[a] * dst.n[a] for a in range(3)]
        jhi = [jlo[a] + jv[a] for a in range(3)]
        for if_ in range(src.n_shards()):
            iidx = src.shard_index(if_)
            iv = src.valid(iidx)
            ilo = [iidx[a] * src.n[a] for a in range(3)]
            ihi = [ilo[a] + iv[a] for a in range(3)]
            glo = [max(ilo[a], jlo[a]) for a in range(3)]
            ghi = [min(ihi[a], jhi[a]) for a in range(3)]
            if any(ghi[a] <= glo[a] for a in range(3)):
                continue
            out.append(
                ChunkMove(
                    src_rank=src_rank[if_],
                    dst_rank=dst_rank[jf],
                    src_off=tuple(
                        src.lo[a] + glo[a] - ilo[a] for a in range(3)
                    ),
                    dst_off=tuple(
                        dst.lo[a] + glo[a] - jlo[a] for a in range(3)
                    ),
                    size=tuple(ghi[a] - glo[a] for a in range(3)),
                )
            )
    return out


def _permutation_rounds(chunks: List[ChunkMove]) -> List[List[ChunkMove]]:
    """Greedy split into rounds with unique senders AND unique receivers —
    the ppermute constraint (bin/_common._dst_unique_rounds' shape)."""
    rounds: List[List[ChunkMove]] = []
    for c in chunks:
        for r in rounds:
            if all(q.src_rank != c.src_rank and q.dst_rank != c.dst_rank for q in r):
                r.append(c)
                break
        else:
            rounds.append([c])
    return rounds


def _round_tables(group: List[ChunkMove], n_ranks: int,
                  src: SideGeometry, dst: SideGeometry) -> Round:
    staging = tuple(
        max(c.size[a] for c in group) for a in range(3)
    )
    send_start = np.zeros((n_ranks, 3), np.int32)
    send_shift = np.zeros((n_ranks, 3), np.int32)
    recv_start = np.zeros((n_ranks, 3), np.int32)
    recv_pos = np.zeros((n_ranks, 3), np.int32)
    recv_size = np.zeros((n_ranks, 3), np.int32)
    for c in group:
        for a in range(3):
            # dynamic_slice clamps a start so the window fits — pass the
            # CLAMPED start so host and device agree on where data sits
            ss = min(c.src_off[a], src.raw[a] - staging[a])
            ws = min(c.dst_off[a], dst.raw[a] - staging[a])
            spos = c.src_off[a] - ss  # data offset inside the staging buffer
            rpos = c.dst_off[a] - ws  # where the receiver needs it
            send_start[c.src_rank, a] = ss
            send_shift[c.src_rank, a] = rpos - spos
            recv_start[c.dst_rank, a] = ws
            recv_pos[c.dst_rank, a] = rpos
            recv_size[c.dst_rank, a] = c.size[a]
    return Round(
        staging=staging,
        pairs=tuple((c.src_rank, c.dst_rank) for c in group),
        send_start=send_start,
        send_shift=send_shift,
        recv_start=recv_start,
        recv_pos=recv_pos,
        recv_size=recv_size,
    )


def plan_redistribution(size, src: SideGeometry, dst: SideGeometry) -> RedistributionPlan:
    """Host-side schedule: union device order, chunk decomposition,
    permutation rounds with their staging shapes and offset tables."""
    size = tuple(Dim3.of(size)) if not isinstance(size, tuple) else size
    union: List = list(src.devices)
    have = {d.id for d in union}
    for d in dst.devices:
        if d.id not in have:
            union.append(d)
            have.add(d.id)
    rank_of = {d.id: i for i, d in enumerate(union)}
    src_rank = {f: rank_of[src.devices[f].id] for f in range(src.n_shards())}
    dst_rank = {f: rank_of[dst.devices[f].id] for f in range(dst.n_shards())}
    chunks = _chunks(src, dst, src_rank, dst_rank)
    rounds = [
        _round_tables(g, len(union), src, dst)
        for g in _permutation_rounds(chunks)
    ]
    return RedistributionPlan(
        size=tuple(size),
        src=src,
        dst=dst,
        union_devices=tuple(union),
        src_rank=src_rank,
        dst_rank=dst_rank,
        rounds=tuple(rounds),
    )


def _union_mesh(plan: RedistributionPlan):
    import numpy as _np
    from jax.sharding import Mesh

    return Mesh(_np.array(plan.union_devices), (UNION_AXIS,))


def _aligned_roll(x, shift, axis: int, extent: int):
    """Cyclic roll by a TRACED per-rank shift: double the buffer along
    ``axis`` and slice the rotated window back out.  The concat is the one
    place the staging footprint exceeds a single buffer (2x, inside the
    STAGING_BOUND_FACTOR)."""
    import jax.numpy as jnp
    from jax import lax

    if extent == 1:
        return x  # a 1-wide axis cannot be misaligned
    doubled = jnp.concatenate([x, x], axis=axis)
    start = [jnp.int32(0)] * doubled.ndim
    start[axis] = jnp.mod(
        jnp.int32(extent) - shift.astype(jnp.int32), jnp.int32(extent)
    )
    sizes = list(x.shape)
    return lax.dynamic_slice(doubled, start, sizes)


def build_redistribute_fn(plan: RedistributionPlan, components: Tuple[int, ...], dtype):
    """The jitted collective schedule for one quantity signature.

    Takes the ``(R, *components, *src.raw)`` stacked source blocks sharded
    over the union mesh; returns the ``(R, *components, *dst.raw)`` stacked
    target blocks (zero shells, valid interiors installed) on the same
    mesh.  Ranks outside the target mesh return zero blocks that are
    simply dropped at re-assembly.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from stencil_tpu import telemetry
    from stencil_tpu.telemetry import names as tm
    from stencil_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = _union_mesh(plan)
    ncomp = len(components)
    rounds = plan.rounds
    dst_raw = plan.dst.raw

    def per_shard(src_block):
        # src_block: (1, *components, *src.raw) — this rank's stacked slice
        rank = lax.axis_index(UNION_AXIS)
        block = src_block[0]
        out = jnp.zeros(components + dst_raw, dtype=dtype)
        with telemetry.annotate(tm.SPAN_RESHARD):
            for rnd in rounds:
                sstart = jnp.asarray(rnd.send_start)[rank]
                sshift = jnp.asarray(rnd.send_shift)[rank]
                rstart = jnp.asarray(rnd.recv_start)[rank]
                rpos = jnp.asarray(rnd.recv_pos)[rank]
                rsize = jnp.asarray(rnd.recv_size)[rank]
                chunk = lax.dynamic_slice(
                    block,
                    [jnp.int32(0)] * ncomp + [sstart[a] for a in range(3)],
                    components + rnd.staging,
                )
                for a in range(3):
                    chunk = _aligned_roll(
                        chunk, sshift[a], ncomp + a, rnd.staging[a]
                    )
                moved = lax.ppermute(chunk, UNION_AXIS, rnd.pairs)
                # masked blend of the valid extent into the write window:
                # 1-D iotas keep the mask at 1 B/cell, and ranks with a
                # zero recv_size blend nothing (the SPMD-uniform no-op)
                masks = []
                for a in range(3):
                    i = jnp.arange(rnd.staging[a], dtype=jnp.int32)
                    masks.append((i >= rpos[a]) & (i < rpos[a] + rsize[a]))
                mask = (
                    masks[0][:, None, None]
                    & masks[1][None, :, None]
                    & masks[2][None, None, :]
                )
                window = lax.dynamic_slice(
                    out,
                    [jnp.int32(0)] * ncomp + [rstart[a] for a in range(3)],
                    components + rnd.staging,
                )
                window = jnp.where(mask, moved, window)
                # stencil-lint: disable=sliver-dus one-shot reshard staging-window write, not a per-step halo path; the traced form is bounds-checked by the redistribute-bounded contract instead
                out = lax.dynamic_update_slice(
                    out,
                    window,
                    [jnp.int32(0)] * ncomp + [rstart[a] for a in range(3)],
                )
        return out[None]

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=P(UNION_AXIS),
        out_specs=P(UNION_AXIS),
        # the offset tables/masks are replicated literals blended into
        # varying blocks — the packed exchange routes run with the same
        # setting for the same reason
        check_vma=False,
    )
    return jax.jit(fn), mesh


def _stack_source(plan: RedistributionPlan, arr, components, dtype):
    """Reinterpret the source global array's per-device shards as the
    ``(R, ...)`` stacked union-mesh array WITHOUT any host round trip.
    Union ranks outside the source mesh contribute one zero block each
    (shard-sized staging, inside the memory bound)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _union_mesh(plan)
    per_shard = components + plan.src.raw
    by_dev = {s.device.id: s.data for s in arr.addressable_shards}
    blocks = []
    for d in plan.union_devices:
        data = by_dev.get(d.id)
        if data is None:
            blocks.append(
                jax.device_put(jnp.zeros((1,) + per_shard, dtype=dtype), d)
            )
        else:
            blocks.append(jnp.reshape(data, (1,) + per_shard))
    shape = (len(plan.union_devices),) + per_shard
    return jax.make_array_from_single_device_arrays(
        shape, NamedSharding(mesh, P(UNION_AXIS)), blocks
    ), mesh


def _assemble_target(plan: RedistributionPlan, stacked, components, dtype,
                     dst_mesh, dst_spec):
    """Per-device target blocks -> the global raw array on the target
    mesh (the sharded layout ``realize()`` allocates)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    by_dev = {s.device.id: s.data for s in stacked.addressable_shards}
    dim = plan.dst.dim
    raw = plan.dst.raw
    gshape = components + tuple(dim[a] * raw[a] for a in range(3))
    sharding = NamedSharding(dst_mesh, dst_spec)
    blocks = []
    for f in range(plan.dst.n_shards()):
        dev = plan.dst.devices[f]
        data = by_dev[dev.id]
        blocks.append(jnp.reshape(data, components + raw))
    # order blocks by the sharding's device->index map so assembly is
    # explicit about which block is which global slice
    index_map = sharding.addressable_devices_indices_map(gshape)
    ordered = []
    by_target_dev = {
        plan.dst.devices[f].id: blocks[f] for f in range(plan.dst.n_shards())
    }
    for dev in index_map:
        ordered.append(by_target_dev[dev.id])
    return jax.make_array_from_single_device_arrays(
        gshape, sharding, ordered
    )


def redistribute_array(plan: RedistributionPlan, arr, components, dtype,
                       dst_mesh, dst_spec, fn=None):
    """Move ONE quantity's global raw array across the plan.  Returns the
    new global array on the target mesh; the source array is left intact
    (the caller installs the result and drops its references).  ``fn``
    reuses a prebuilt schedule: jitted functions are fresh closures per
    ``build_redistribute_fn`` call, so a multi-quantity caller must cache
    per (components, dtype) signature or pay one trace+compile per
    quantity (``DistributedDomain.reshard`` does)."""
    components = tuple(components)
    stacked, _ = _stack_source(plan, arr, components, dtype)
    if fn is None:
        fn, _ = build_redistribute_fn(plan, components, dtype)
    out = fn(stacked)
    return _assemble_target(plan, out, components, dtype, dst_mesh, dst_spec)


def redistribution_program(plan: RedistributionPlan, components=(), dtype=None):
    """(fn, example_arg, meta) for tracing/verification: the exact jitted
    schedule ``redistribute_array`` runs, plus the staging bound the
    ``redistribute-bounded`` contract enforces on its traced form."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    dtype = jnp.float32 if dtype is None else dtype
    components = tuple(components)
    fn, mesh = build_redistribute_fn(plan, components, dtype)
    shape = (len(plan.union_devices),) + components + plan.src.raw
    example = jax.ShapeDtypeStruct(
        shape, dtype, sharding=NamedSharding(mesh, P(UNION_AXIS))
    )
    cell = 1
    for c in components:
        cell *= c
    meta = {
        "bound_bytes": plan.bound_bytes(jnp.dtype(dtype).itemsize, cell),
        "rounds": len(plan.rounds),
        "union_ranks": len(plan.union_devices),
    }
    return fn, example, meta
