"""Device-mesh construction for the 3D domain decomposition.

The TPU-native replacement for the reference's rank/GPU assignment machinery
(stencil.hpp:133-246 + partition.hpp placement): a ``jax.sharding.Mesh`` with
axes ``('x', 'y', 'z')`` whose device grid comes from a ``Placement``.  All
five reference transports ride this mesh as ``lax.ppermute`` (SURVEY.md §2.2
TPU mapping).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from stencil_tpu.core.dim3 import Dim3
from stencil_tpu.core.radius import Radius
from stencil_tpu.parallel.partition import ManualPartition, NodePartition
from stencil_tpu.parallel.placement import Placement, make_placement
from stencil_tpu.parallel.topology import num_processes
from stencil_tpu.utils.config import PlacementStrategy

MESH_AXES = ("x", "y", "z")


def choose_partition(size, radius: Radius, devices: Sequence) -> NodePartition:
    """Two-level min-interface partition over the device fleet: DCN processes
    play the reference's 'nodes', per-process devices its 'gpus'
    (partition.hpp:647: NodeAware ctor builds NodePartition(nNodes, gpusPerNode))."""
    n_proc = num_processes(devices)
    per_proc = len(devices) // n_proc
    return NodePartition(Dim3.of(size), radius, n_proc, per_proc)


def make_mesh(
    size,
    radius: Radius,
    devices: Optional[Sequence] = None,
    strategy: PlacementStrategy = PlacementStrategy.NodeAware,
    force_dim=None,
):
    """Partition ``size`` over ``devices`` and build the (Mesh, Placement).
    ``force_dim`` bypasses the splitters with a user-specified grid (manual
    partition, the reference's future-work item)."""
    if devices is None:
        devices = jax.devices()
    if force_dim is not None:
        part = ManualPartition(Dim3.of(size), force_dim)
        if part.dim().flatten() != len(devices):
            raise ValueError(
                f"manual partition {part.dim()} needs {part.dim().flatten()} "
                f"devices, have {len(devices)}"
            )
    else:
        part = choose_partition(size, radius, devices)
    placement = make_placement(strategy, part, devices, radius)
    mesh = Mesh(placement.device_grid(), MESH_AXES)
    return mesh, placement


def mesh_from_grid(grid: np.ndarray) -> Mesh:
    return Mesh(grid, MESH_AXES)
