"""Device-mesh construction for the 3D domain decomposition.

The TPU-native replacement for the reference's rank/GPU assignment machinery
(stencil.hpp:133-246 + partition.hpp placement): a ``jax.sharding.Mesh`` with
axes ``('x', 'y', 'z')`` whose device grid comes from a ``Placement``.  All
five reference transports ride this mesh as ``lax.ppermute`` (SURVEY.md §2.2
TPU mapping).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from stencil_tpu.core.dim3 import Dim3
from stencil_tpu.core.radius import Radius
from stencil_tpu.parallel.partition import NodePartition
from stencil_tpu.parallel.placement import Placement, make_placement
from stencil_tpu.parallel.topology import num_processes
from stencil_tpu.utils.config import PlacementStrategy

MESH_AXES = ("x", "y", "z")


def choose_partition(size, radius: Radius, devices: Sequence) -> NodePartition:
    """Two-level min-interface partition over the device fleet: DCN processes
    play the reference's 'nodes', per-process devices its 'gpus'
    (partition.hpp:647: NodeAware ctor builds NodePartition(nNodes, gpusPerNode))."""
    n_proc = num_processes(devices)
    per_proc = len(devices) // n_proc
    return NodePartition(Dim3.of(size), radius, n_proc, per_proc)


def make_mesh(
    size,
    radius: Radius,
    devices: Optional[Sequence] = None,
    strategy: PlacementStrategy = PlacementStrategy.NodeAware,
):
    """Partition ``size`` over ``devices`` and build the (Mesh, Placement)."""
    if devices is None:
        devices = jax.devices()
    part = choose_partition(size, radius, devices)
    placement = make_placement(strategy, part, devices, radius)
    mesh = Mesh(placement.device_grid(), MESH_AXES)
    return mesh, placement


def mesh_from_grid(grid: np.ndarray) -> Mesh:
    return Mesh(grid, MESH_AXES)
