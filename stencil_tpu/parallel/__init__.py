"""Decomposition, placement, and device-mesh construction (reference L5/L1)."""

from stencil_tpu.parallel.partition import RankPartition, NodePartition, prime_factors
from stencil_tpu.parallel.qap import qap_cost, qap_solve, qap_solve_catch
from stencil_tpu.parallel.placement import Placement, TrivialPlacement, NodeAwarePlacement

__all__ = [
    "RankPartition",
    "NodePartition",
    "prime_factors",
    "qap_cost",
    "qap_solve",
    "qap_solve_catch",
    "Placement",
    "TrivialPlacement",
    "NodeAwarePlacement",
]
