"""Quadratic assignment for topology-aware placement.

Parity target: ``qap::solve`` / ``qap::solve_catch`` (reference
include/stencil/qap.hpp:50-172).  Given a weight (communication) matrix ``w``
and a distance matrix ``d``, find the bijection ``f`` minimizing
``sum_ab w[a][b] * d[f[a]][f[b]]`` — with the reference's ``0 * inf = 0``
guard (qap.hpp:15-20).

* ``qap_solve`` — exact, O(n!) over all permutations (qap.hpp:50-75); the
  reference calls this per-node for <= ~6 GPUs.
* ``qap_solve_catch`` — "CRAFT" 2-opt pairwise-swap hill climbing with
  incremental cost updates (qap.hpp:77-172); the scalable one, used here for
  pod-sized meshes.

A C++ implementation (``native/qap.cpp``) is used when the shared library has
been built (it is ~100x faster for the exact solver at n>=8); these Python
versions are the always-available fallback and the semantic spec.
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Sequence, Tuple

import numpy as np


def _cost_product(we: float, de: float) -> float:
    # qap.hpp:15-20: avoid 0 * inf = nan
    if we == 0 or de == 0:
        return 0.0
    return we * de


def qap_cost(w: np.ndarray, d: np.ndarray, f: Sequence[int]) -> float:
    """qap.hpp:23-47."""
    w = np.asarray(w, dtype=float)
    d = np.asarray(d, dtype=float)
    n = w.shape[0]
    assert w.shape == (n, n) and d.shape == (n, n) and len(f) == n
    # vectorized with the 0*inf guard: mask where either factor is zero
    df = d[np.ix_(f, f)]
    prod = np.where((w == 0) | (df == 0), 0.0, w * df)
    return float(prod.sum())


def qap_solve(w: np.ndarray, d: np.ndarray) -> Tuple[List[int], float]:
    """Exact exhaustive search (qap.hpp:50-75).  O(n!)."""
    w = np.asarray(w, dtype=float)
    d = np.asarray(d, dtype=float)
    n = w.shape[0]
    best_f = list(range(n))
    best_cost = qap_cost(w, d, best_f)
    for perm in itertools.permutations(range(n)):
        c = qap_cost(w, d, perm)
        if c < best_cost:
            best_cost = c
            best_f = list(perm)
    return best_f, best_cost


def _masked_prod(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    # elementwise cost_product (qap.hpp:15-20): 0 * inf = 0
    return np.where((a == 0) | (b == 0), 0.0, a * b)


def _swap_delta(w: np.ndarray, d: np.ndarray, f: List[int], i: int, j: int) -> float:
    """Cost change from swapping f[i], f[j] (incremental update,
    qap.hpp:108-147), including the diagonal overlap handling.  Vectorized
    over k; semantically identical to the reference's loop."""
    fa = np.asarray(f)

    def affected(fi_sub: int, fj_sub: int) -> float:
        s = _masked_prod(w[i, :], d[fi_sub, fa]).sum()
        s += _masked_prod(w[j, :], d[fj_sub, fa]).sum()
        col = _masked_prod(w[:, i], d[fa, fi_sub]) + _masked_prod(w[:, j], d[fa, fj_sub])
        s += col.sum() - col[i] - col[j]
        # the two row terms above used d[fi_sub, fa] with fa holding the
        # UNswapped values at i and j; patch those four entries
        s -= _masked_prod(w[i, i], d[fi_sub, fa[i]]) + _masked_prod(w[i, j], d[fi_sub, fa[j]])
        s -= _masked_prod(w[j, i], d[fj_sub, fa[i]]) + _masked_prod(w[j, j], d[fj_sub, fa[j]])
        fi_cur, fj_cur = fi_sub, fj_sub
        s += _masked_prod(w[i, i], d[fi_cur, fi_cur]) + _masked_prod(w[i, j], d[fi_cur, fj_cur])
        s += _masked_prod(w[j, i], d[fj_cur, fi_cur]) + _masked_prod(w[j, j], d[fj_cur, fj_cur])
        return float(s)

    before = affected(f[i], f[j])
    after = affected(f[j], f[i])
    return after - before


def qap_solve_catch(w: np.ndarray, d: np.ndarray) -> Tuple[List[int], float]:
    """2-opt hill climbing (qap.hpp:77-172): repeatedly take the best
    single-pair swap until no swap improves."""
    w = np.asarray(w, dtype=float)
    d = np.asarray(d, dtype=float)
    n = w.shape[0]
    best_f = list(range(n))
    best_cost = qap_cost(w, d, best_f)

    improved = True
    while improved:
        improved = False
        impr_swap: Optional[Tuple[int, int]] = None
        impr_cost = best_cost
        for i in range(n):
            for j in range(i + 1, n):
                c = best_cost + _swap_delta(w, d, best_f, i, j)
                if c < impr_cost:
                    impr_cost = c
                    impr_swap = (i, j)
                    improved = True
        if improved:
            i, j = impr_swap
            best_f[i], best_f[j] = best_f[j], best_f[i]
            best_cost = impr_cost
    return best_f, best_cost


def solve_auto(w: np.ndarray, d: np.ndarray, exact_limit: int = 8) -> Tuple[List[int], float]:
    """Exact for small n (like the reference's per-node exact solve for <=6
    GPUs, partition.hpp:802-803), 2-opt beyond.  Prefers the native C++
    implementation when built."""
    try:
        from stencil_tpu.parallel import native_qap

        return native_qap.solve_auto(w, d, exact_limit)
    except (ImportError, OSError):
        pass
    n = np.asarray(w).shape[0]
    if n <= exact_limit:
        return qap_solve(w, d)
    return qap_solve_catch(w, d)
