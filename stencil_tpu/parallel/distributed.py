"""Multi-host initialization and coordination.

Parity target: the reference's MPI world setup — ``MPI_Init`` in every driver
main, ``MpiTopology``'s shared-memory communicator split (mpi_topology.hpp:20)
and the rank-0 gather/broadcast patterns (partition.hpp:653-712, 833-835).

TPU-native design: ``jax.distributed.initialize`` joins the processes of a
multi-host pod (or multi-slice DCN job); afterwards ``jax.devices()`` spans
every host's chips and the 3D mesh built by ``make_mesh`` automatically
covers them — ``NodePartition`` splits the domain process-first (DCN) then
per-process (ICI), exactly the reference's node x GPU two-level hierarchy.
Host-side coordination (the reference's Allgather/Bcast of placement state)
rides ``jax.experimental.multihost_utils``.

On a single process every function is a no-op/identity, so drivers and tests
run unchanged anywhere.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join a multi-host job (MPI_Init analog).  With no arguments JAX reads
    the cluster environment (TPU pod metadata / SLURM / OpenMPI env vars);
    single-process runs skip initialization entirely."""
    if num_processes is None and coordinator_address is None:
        # auto mode: initialize ONLY when a cluster environment is visibly
        # present — and then let real failures propagate (a swallowed
        # coordinator error would silently degrade a pod job to independent
        # single-host runs)
        # only explicit coordinator addresses count (job-scheduler vars like
        # SLURM_JOB_ID or a polluted TPU_WORKER_HOSTNAMES don't imply jax can
        # derive a coordinator; callers in such clusters pass arguments)
        cluster_markers = (
            "JAX_COORDINATOR_ADDRESS",
            "COORDINATOR_ADDRESS",
            "MEGASCALE_COORDINATOR_ADDRESS",
        )
        import os

        if not any(os.environ.get(k) for k in cluster_markers):
            return  # plain single-process run
        jax.distributed.initialize()
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def barrier(name: str = "stencil_barrier") -> None:
    """MPI_Barrier analog across hosts (no-op single-process)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def broadcast_from_host0(pytree):
    """MPI_Bcast analog: every process receives host 0's value
    (partition.hpp:833-835 placement broadcast)."""
    if jax.process_count() == 1:
        return pytree
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(pytree)


def allgather_hosts(value: np.ndarray) -> np.ndarray:
    """MPI_Allgather analog: stack every process's value along axis 0."""
    if jax.process_count() == 1:
        return np.asarray(value)[None]
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(value)
