"""Halo geometry math for one subdomain.

Parity target: the geometry half of ``LocalDomain`` (reference
include/stencil/local_domain.cuh:33-349 + src/local_domain.cu:14-95) plus the
interior/exterior region split (src/stencil.cu:567-666).  The device-memory
half of LocalDomain (cudaMalloc double buffers, device pointer tables) does not
exist on TPU: per-chip storage is a shard of a ``jax.Array`` and lives in
``stencil_tpu.domain``.

``LocalSpec`` is pure host-side metadata: compute size ``sz``, global
``origin``, and ``Radius``.  All the invariants the reference's tests pin are
reproduced here:

* ``halo_pos(dir, halo)`` — offset (from allocation start) of the halo
  (``halo=True``) or interior-edge (``halo=False``) region on side ``dir``
  (src/local_domain.cu:56-95).
* ``halo_extent(dir)`` — region size: ``sz`` on 0-axes, ``radius.dir(dir)`` on
  +-1 axes (local_domain.cuh:285-298).
* the ``-dir`` convention: a message sent in direction ``d`` packs the
  interior region at ``halo_pos(d, False)`` with extent ``halo_extent(-d)``
  and unpacks into ``halo_pos(-d, True)`` with extent ``halo_extent(-d)``
  (packer.cuh:91-93, 271-273) — the *receiver's* halo width rules the size.
"""

from __future__ import annotations

import dataclasses
from typing import List

import numpy as np

from stencil_tpu.core.dim3 import Dim3, Rect3
from stencil_tpu.core.direction_map import DIRECTIONS_26
from stencil_tpu.core.radius import Radius


def halo_extent(direction: Dim3, sz: Dim3, radius: Radius) -> Dim3:
    """Point-size of the halo region on side ``dir`` (local_domain.cuh:285-298).

    Each nonzero axis contributes that axis's *face* radius
    (``radius.x(dir.x)`` etc., NOT the full-direction radius) — so an edge
    region is face-radius-wide on both its axes.  ``dir == (0,0,0)`` returns
    ``sz``.
    """
    d = Dim3.of(direction)
    return Dim3(
        sz.x if d.x == 0 else radius.x(d.x),
        sz.y if d.y == 0 else radius.y(d.y),
        sz.z if d.z == 0 else radius.z(d.z),
    )


@dataclasses.dataclass(frozen=True)
class LocalSpec:
    """Geometry of one per-chip subdomain (shell-carrying layout)."""

    sz: Dim3
    origin: Dim3
    radius: Radius

    @staticmethod
    def make(sz, origin, radius: Radius) -> "LocalSpec":
        return LocalSpec(Dim3.of(sz), Dim3.of(origin), radius)

    # --- allocation shape ----------------------------------------------------
    def raw_size(self) -> Dim3:
        """Allocation extent: sz + negative + positive face radii per axis
        (local_domain.cuh:309-313)."""
        r = self.radius
        return Dim3(
            self.sz.x + r.x(-1) + r.x(1),
            self.sz.y + r.y(-1) + r.y(1),
            self.sz.z + r.z(-1) + r.z(1),
        )

    # --- halo position/extent (src/local_domain.cu:56-95) --------------------
    def halo_pos(self, direction, halo: bool) -> Dim3:
        d = Dim3.of(direction)
        assert d.all_gt(-2) and d.all_lt(2)
        r = self.radius

        def one(axis: int, s: int) -> int:
            if s == 1:
                return self.sz[axis] + (r.axis(axis, -1) if halo else 0)
            if s == -1:
                return 0 if halo else r.axis(axis, -1)
            return r.axis(axis, -1)

        return Dim3(one(0, d.x), one(1, d.y), one(2, d.z))

    def halo_extent(self, direction) -> Dim3:
        return halo_extent(direction, self.sz, self.radius)

    def halo_coords(self, direction, halo: bool) -> Rect3:
        """Global coordinates of the region (src/local_domain.cu:14-32)."""
        pos = self.halo_pos(direction, halo)
        ext = self.halo_extent(direction)
        pos = pos - self.radius.lo() + self.origin
        return Rect3(pos, pos + ext)

    def halo_bytes(self, direction, itemsize: int) -> int:
        """Bytes of one quantity's halo on side ``dir`` (local_domain.cuh:301-303)."""
        return int(itemsize) * self.halo_extent(direction).flatten()

    # --- compute region (global coords) --------------------------------------
    def compute_region(self) -> Rect3:
        return Rect3(self.origin, self.origin + self.sz)

    def full_region(self) -> Rect3:
        """Compute region plus the halo shell, in global coords
        (local_domain.cuh:213-227 get_full_region analog)."""
        return Rect3(self.origin - self.radius.lo(), self.origin + self.sz + self.radius.hi())

    # --- interior/exterior split (src/stencil.cu:567-666) --------------------
    def interior(self) -> Rect3:
        """Compute region shrunk per-direction so no point reads a halo cell."""
        return shrink_by_radius(self.compute_region(), self.radius)

    def exterior(self) -> List[Rect3]:
        """Non-overlapping face slabs covering compute-region minus interior,
        via the reference's slide-in construction (src/stencil.cu:616-666):
        order +x, +y, +z, -x, -y, -z."""
        return exterior_of(self.compute_region(), self.interior())

    # --- local (allocation-relative) views -----------------------------------
    def to_local(self, r: Rect3) -> Rect3:
        """Global-coords region -> allocation-relative indices."""
        shift = self.radius.lo() - self.origin
        return Rect3(r.lo + shift, r.hi + shift)

    def local_slices(self, r: Rect3):
        """numpy-style index tuple (x, y, z order) for a global-coords region."""
        lr = self.to_local(r)
        return (
            slice(lr.lo.x, lr.hi.x),
            slice(lr.lo.y, lr.hi.y),
            slice(lr.lo.z, lr.hi.z),
        )

    def interior_slices(self):
        return self.local_slices(self.compute_region())


def shrink_by_radius(com: Rect3, radius: Radius) -> Rect3:
    """Shrink a region per-direction so no point inside reads outside it
    (the interior construction, src/stencil.cu:567-610; also the per-sub-step
    valid-region shrink under a halo multiplier)."""
    lo = [com.lo.x, com.lo.y, com.lo.z]
    hi = [com.hi.x, com.hi.y, com.hi.z]
    for d in DIRECTIONS_26:
        rad = radius.dir(d)
        for axis in range(3):
            if d[axis] < 0:
                lo[axis] = max(com.lo[axis] + rad, lo[axis])
            elif d[axis] > 0:
                hi[axis] = min(com.hi[axis] - rad, hi[axis])
    return Rect3(Dim3(*lo), Dim3(*hi))


def exterior_of(com: Rect3, int_reg: Rect3) -> List[Rect3]:
    """Non-overlapping face slabs covering ``com`` minus ``int_reg`` via the
    slide-in construction (src/stencil.cu:616-666): +x, +y, +z, -x, -y, -z."""
    clo = [com.lo.x, com.lo.y, com.lo.z]
    chi = [com.hi.x, com.hi.y, com.hi.z]
    ilo = [int_reg.lo.x, int_reg.lo.y, int_reg.lo.z]
    ihi = [int_reg.hi.x, int_reg.hi.y, int_reg.hi.z]
    out: List[Rect3] = []
    for axis in range(3):  # +x, +y, +z
        if ihi[axis] != chi[axis]:
            lo = list(clo)
            hi = list(chi)
            lo[axis] = ihi[axis]
            out.append(Rect3(Dim3(*lo), Dim3(*hi)))
            chi[axis] = ihi[axis]
    for axis in range(3):  # -x, -y, -z
        if ilo[axis] != clo[axis]:
            lo = list(clo)
            hi = list(chi)
            hi[axis] = ilo[axis]
            out.append(Rect3(Dim3(*lo), Dim3(*hi)))
            clo[axis] = ilo[axis]
    return out


def exchange_bytes(spec: LocalSpec, itemsizes) -> int:
    """Total bytes one subdomain receives per exchange, all quantities, all 26
    directions — the analytic model behind the reference's per-method byte
    counters (src/stencil.cu:260-361).  A direction contributes iff the radius
    in the *opposite* direction is nonzero (src/stencil.cu:149: skip dir if
    ``radius.dir(-dir) == 0``)."""
    total = 0
    for d in DIRECTIONS_26:
        if spec.radius.dir(-d) == 0:
            continue
        ext = spec.halo_extent(-d).flatten()
        total += sum(int(s) for s in itemsizes) * ext
    return total


def sweep_bytes(spec: LocalSpec, itemsizes) -> int:
    """Bytes one subdomain actually RECEIVES per 3-axis-sweep exchange
    (ops/exchange.py): each axis's slabs span the FULL raw extent of the
    other axes — including their halos — so edge/corner data rides along
    (and transits once per participating axis).  Whenever more than one axis
    has a radius this exceeds ``exchange_bytes`` (the reference's 26-message
    model, which counts each edge/corner once): the honest denominator for
    sweep-based B/s.
    """
    raw = spec.raw_size()
    r = spec.radius
    total = 0
    itemsize_sum = sum(int(s) for s in itemsizes)
    for axis in range(3):
        others = [raw[b] for b in range(3) if b != axis]
        plane = others[0] * others[1]
        # the +axis message has the receiver's -axis halo width and vice versa
        total += itemsize_sum * plane * (r.axis(axis, -1) + r.axis(axis, +1))
    return total


#: receive-side direction name per (axis index, sign): ``low`` receives the
#: -1 neighbor's slab (ops/exchange.py ``_shift_from_low``), ``high`` the +1
#: neighbor's — the vocabulary of the ``exchange.<axis>.<side>`` spans
HOP_SIDES = ((-1, "low"), (+1, "high"))


def sweep_hop_bytes(spec: LocalSpec, itemsizes) -> dict:
    """``sweep_bytes`` decomposed per mesh hop: bytes one subdomain receives
    per exchange over each (axis index, side) message of the 3-axis-sweep
    implementation, keyed ``(axis, side)`` with side in ``low``/``high``.
    Values sum to ``sweep_bytes`` — the honest per-LINK traffic model for
    the comms roofline (edge/corner data transits once per participating
    axis, so the sum exceeds the 26-message ``exchange_bytes``)."""
    raw = spec.raw_size()
    r = spec.radius
    itemsize_sum = sum(int(s) for s in itemsizes)
    out = {}
    for axis in range(3):
        others = [raw[b] for b in range(3) if b != axis]
        plane = others[0] * others[1]
        for sign, side in HOP_SIDES:
            # the slab received on ``side`` has that side's halo width
            out[(axis, side)] = itemsize_sum * plane * r.axis(axis, sign)
    return out


def ripple_value(p: Dim3) -> float:
    """The analytic test field from the reference's exchange tests
    (test_exchange.cu:14-38): ``x + ripple[x%4] + y + ripple[y%4] + z +
    ripple[z%4]`` with ripple = [0, .25, 0, -.25].  Any wrong halo byte is
    detectable without a reference simulation."""
    ripple = (0.0, 0.25, 0.0, -0.25)
    return p.x + ripple[p.x % 4] + p.y + ripple[p.y % 4] + p.z + ripple[p.z % 4]


def ripple_field(lo: Dim3, ext: Dim3, dtype=np.float32) -> np.ndarray:
    """Vectorized ripple over a box, returned with (x, y, z) index order."""
    ripple = np.array([0.0, 0.25, 0.0, -0.25])

    def axis_vals(start, n):
        idx = np.arange(start, start + n)
        return idx + ripple[idx % 4]

    vx = axis_vals(lo.x, ext.x)[:, None, None]
    vy = axis_vals(lo.y, ext.y)[None, :, None]
    vz = axis_vals(lo.z, ext.z)[None, None, :]
    return (vx + vy + vz).astype(dtype)
