"""Global-coordinate views over a halo-carrying local block.

Parity target: ``Accessor<T>`` (reference include/stencil/accessor.hpp:13-45),
which lets stencil kernels index by global 3D point, oblivious to halo
offsets.  On TPU the idiomatic analog is *shifted slicing*: a stencil term
``src[o + (dx,dy,dz)]`` over the whole compute region is the interior-sized
slice of the shell-carrying block offset by ``(dx,dy,dz)``.  XLA fuses the
shifted slices into one vectorized loop — this is the stencil-kernel writing
surface of the framework.

``Accessor`` works on anything sliceable with numpy basic indexing (numpy
arrays and jax arrays alike), so the same user kernel runs in tests (numpy),
under ``jit`` (traced jax), and inside ``shard_map`` (per-shard blocks).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from stencil_tpu.core.dim3 import Dim3, Rect3


@dataclasses.dataclass(frozen=True, eq=False)
class Accessor:
    """View of a raw (shell-carrying) block addressed in global coordinates.

    ``raw`` has extent ``spec.raw_size()`` with index order (x, y, z);
    ``origin`` is the global coordinate of the first *interior* point;
    ``lo_off`` is the shell width on the negative side per axis (so global
    point ``p`` lives at raw index ``p - origin + lo_off``).
    """

    raw: Any
    origin: Dim3
    lo_off: Dim3

    def __getitem__(self, p) -> Any:
        """Scalar read at a global point (accessor.hpp:27-40)."""
        p = Dim3.of(p)
        i = p - self.origin + self.lo_off
        return self.raw[i.x, i.y, i.z]

    def region(self, r: Rect3) -> Any:
        """Slice a global-coords region out of the raw block."""
        lo = r.lo - self.origin + self.lo_off
        hi = r.hi - self.origin + self.lo_off
        return self.raw[lo.x : hi.x, lo.y : hi.y, lo.z : hi.z]

    def shifted(self, region: Rect3, d) -> Any:
        """``src[o + d]`` for every ``o`` in ``region`` — the stencil-term
        primitive.  Returns an array of ``region.extent()`` shape."""
        d = Dim3.of(d)
        return self.region(Rect3(region.lo + d, region.hi + d))
