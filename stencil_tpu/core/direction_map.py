"""Per-direction tables over the 27 neighbor directions.

Parity target: ``DirectionMap<T>`` (reference include/stencil/direction_map.hpp:11):
a 3x3x3 table indexed by a direction vector with components in {-1, 0, 1}.
"""

from __future__ import annotations

from typing import Generic, List, TypeVar

from stencil_tpu.core.dim3 import Dim3

T = TypeVar("T")

#: The 26 neighbor directions (all of {-1,0,1}^3 minus the origin), in the
#: reference's lexicographic Message order (x, then y, then z most-to-least
#: significant — tx_common.hpp:14-21 sorts Messages by Dim3's operator<,
#: dim3.hpp:78-92).
DIRECTIONS_26: List[Dim3] = [
    Dim3(x, y, z)
    for x in (-1, 0, 1)
    for y in (-1, 0, 1)
    for z in (-1, 0, 1)
    if not (x == 0 and y == 0 and z == 0)
]

#: Face directions only (6).
FACE_DIRECTIONS: List[Dim3] = [d for d in DIRECTIONS_26 if abs(d.x) + abs(d.y) + abs(d.z) == 1]
#: Edge directions (12).
EDGE_DIRECTIONS: List[Dim3] = [d for d in DIRECTIONS_26 if abs(d.x) + abs(d.y) + abs(d.z) == 2]
#: Corner directions (8).
CORNER_DIRECTIONS: List[Dim3] = [d for d in DIRECTIONS_26 if abs(d.x) + abs(d.y) + abs(d.z) == 3]


class DirectionMap(Generic[T]):
    """3x3x3 table indexed by direction in {-1,0,1}^3 (direction_map.hpp:11-57)."""

    __slots__ = ("_data",)

    def __init__(self, fill: T = 0):
        self._data = [fill for _ in range(27)]

    @staticmethod
    def _index(x: int, y: int, z: int) -> int:
        assert -1 <= x <= 1 and -1 <= y <= 1 and -1 <= z <= 1, (x, y, z)
        return (z + 1) * 9 + (y + 1) * 3 + (x + 1)

    def at_dir(self, x: int, y: int, z: int) -> T:
        return self._data[self._index(x, y, z)]

    def set_dir(self, x: int, y: int, z: int, v: T) -> None:
        self._data[self._index(x, y, z)] = v

    def __getitem__(self, d) -> T:
        d = Dim3.of(d)
        return self.at_dir(d.x, d.y, d.z)

    def __setitem__(self, d, v: T) -> None:
        d = Dim3.of(d)
        self.set_dir(d.x, d.y, d.z, v)

    def __eq__(self, o) -> bool:
        return isinstance(o, DirectionMap) and self._data == o._data

    def __hash__(self) -> int:
        return hash(tuple(self._data))

    def copy(self) -> "DirectionMap[T]":
        m = DirectionMap()
        m._data = list(self._data)
        return m

    def __repr__(self) -> str:
        entries = ", ".join(f"{d}:{self[d]}" for d in DIRECTIONS_26 if self[d])
        return f"DirectionMap({entries})"
