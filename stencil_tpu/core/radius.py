"""Per-direction stencil radius.

Parity target: ``Radius`` (reference include/stencil/radius.hpp:14-105).
The radius is a 26-direction table of halo widths; uneven radii per direction
are first-class (e.g. +x=2, -x=1).  Factories match the reference:
``constant(r)`` (radius.hpp:81) and ``face_edge_corner(f, e, c)``
(radius.hpp:95, zeroes the center entry).

TPU-design note: the shell-carrying shard layout allocates per-axis halo
widths from the *face* radii (exactly like the reference's ``raw_size``,
local_domain.cuh:309-313), so edge/corner radii must not exceed the face radii
of their constituent axes — ``validate()`` enforces what the reference
implicitly assumes.
"""

from __future__ import annotations

from stencil_tpu.core.dim3 import Dim3
from stencil_tpu.core.direction_map import (
    CORNER_DIRECTIONS,
    DIRECTIONS_26,
    EDGE_DIRECTIONS,
    FACE_DIRECTIONS,
    DirectionMap,
)


class Radius:
    __slots__ = ("_rads",)

    def __init__(self):
        self._rads: DirectionMap = DirectionMap(0)

    # --- accessors (radius.hpp:19-41) ----------------------------------------
    def dir(self, x, y=None, z=None) -> int:
        if y is None:
            d = Dim3.of(x)
            return self._rads.at_dir(d.x, d.y, d.z)
        return self._rads.at_dir(x, y, z)

    def set_dir(self, d, r: int) -> None:
        d = Dim3.of(d)
        self._rads.set_dir(d.x, d.y, d.z, int(r))

    def x(self, d: int) -> int:
        return self.dir(d, 0, 0)

    def y(self, d: int) -> int:
        return self.dir(0, d, 0)

    def z(self, d: int) -> int:
        return self.dir(0, 0, d)

    def axis(self, axis: int, sign: int) -> int:
        """Face radius along numbered axis (0=x, 1=y, 2=z)."""
        d = [0, 0, 0]
        d[axis] = sign
        return self.dir(*d)

    def scaled(self, k: int) -> "Radius":
        """A radius with every direction multiplied by ``k`` — the halo
        multiplier (reference README.md future list: exchange every k steps
        with k*r-wide halos)."""
        out = Radius()
        for sx in (-1, 0, 1):
            for sy in (-1, 0, 1):
                for sz in (-1, 0, 1):
                    if (sx, sy, sz) != (0, 0, 0):
                        out.set_dir(Dim3(sx, sy, sz), self.dir(sx, sy, sz) * k)
        return out

    # --- mutators (radius.hpp:46-79) -----------------------------------------
    def set_face(self, r: int) -> "Radius":
        for d in FACE_DIRECTIONS:
            self.set_dir(d, r)
        return self

    def set_edge(self, r: int) -> "Radius":
        for d in EDGE_DIRECTIONS:
            self.set_dir(d, r)
        return self

    def set_corner(self, r: int) -> "Radius":
        for d in CORNER_DIRECTIONS:
            self.set_dir(d, r)
        return self

    # --- factories (radius.hpp:81-104) ---------------------------------------
    @staticmethod
    def constant(r: int) -> "Radius":
        ret = Radius()
        for d in DIRECTIONS_26:
            ret.set_dir(d, r)
        # NOTE: reference `constant` also sets the center entry (radius.hpp:83-90
        # iterates all 27); it is never read through dir() with (0,0,0) by halo
        # math, but we match it for table equality.
        ret._rads.set_dir(0, 0, 0, int(r))
        return ret

    @staticmethod
    def face_edge_corner(face: int, edge: int, corner: int) -> "Radius":
        ret = Radius()
        ret.set_face(face)
        ret.set_edge(edge)
        ret.set_corner(corner)
        ret._rads.set_dir(0, 0, 0, 0)
        return ret

    @staticmethod
    def from_dict(entries) -> "Radius":
        """Build from {direction: radius}; unspecified directions are 0."""
        ret = Radius()
        for d, r in dict(entries).items():
            ret.set_dir(Dim3.of(d), r)
        return ret

    # --- derived --------------------------------------------------------------
    def lo(self) -> Dim3:
        """Per-axis negative-side face widths (the shell's low offsets)."""
        return Dim3(self.x(-1), self.y(-1), self.z(-1))

    def hi(self) -> Dim3:
        """Per-axis positive-side face widths."""
        return Dim3(self.x(1), self.y(1), self.z(1))

    def max_radius(self) -> int:
        return max(self.dir(d) for d in DIRECTIONS_26)

    def validate(self) -> None:
        """Edge/corner radii must fit inside the face-radius shell (see module doc)."""
        for d in DIRECTIONS_26:
            r = self.dir(d)
            for axis in range(3):
                s = d[axis]
                if s != 0 and r > self.axis(axis, s):
                    raise ValueError(
                        f"radius {r} in direction {d} exceeds face radius "
                        f"{self.axis(axis, s)} on axis {axis} sign {s}; the halo "
                        f"shell is allocated from face radii (local_domain.cuh:309)"
                    )

    def __eq__(self, o) -> bool:
        return isinstance(o, Radius) and self._rads == o._rads

    def __hash__(self) -> int:
        # hash of current contents; like any mutable-keyed dict use, mutating
        # after insertion is on the caller (needed so frozen LocalSpec hashes)
        return hash(self._rads)

    def __repr__(self) -> str:
        vals = {tuple(d): self.dir(d) for d in DIRECTIONS_26 if self.dir(d)}
        return f"Radius({vals})"
