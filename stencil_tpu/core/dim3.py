"""3-component integer vectors and axis-aligned boxes.

Parity targets: ``Dim3`` (reference include/stencil/dim3.hpp:25) and ``Rect3``
(reference include/stencil/rect3.hpp:13).  The semantics replicated here and
pinned by tests:

* component-wise arithmetic (+, -, *, //, %) between ``Dim3`` s and with ints
* lexicographic ordering with x most significant (dim3.hpp:78-92)
* ``flatten`` = x*y*z (dim3.hpp:76)
* periodic ``wrap(lims)`` (dim3.hpp:216-231): adds ``lims`` then mods, so a
  single-step out-of-range coordinate in [-lims, 2*lims) wraps correctly
* ``all_lt / all_gt / any_lt / any_gt`` predicates (dim3.hpp:190-214)

The class is immutable and hashable so it can key dicts of per-direction state
(the reference uses ``std::map<Dim3, ...>`` keyed on lexicographic order).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Iterator, Tuple


@dataclasses.dataclass(frozen=True, order=False)
class Dim3:
    x: int = 0
    y: int = 0
    z: int = 0

    # --- construction helpers -------------------------------------------------
    @staticmethod
    def of(v) -> "Dim3":
        """Coerce an int, 3-tuple, or Dim3 into a Dim3."""
        if isinstance(v, Dim3):
            return v
        if isinstance(v, int):
            return Dim3(v, v, v)
        x, y, z = v
        return Dim3(int(x), int(y), int(z))

    def __post_init__(self):
        object.__setattr__(self, "x", int(self.x))
        object.__setattr__(self, "y", int(self.y))
        object.__setattr__(self, "z", int(self.z))

    # --- iteration / conversion ----------------------------------------------
    def __iter__(self) -> Iterator[int]:
        yield self.x
        yield self.y
        yield self.z

    def tuple(self) -> Tuple[int, int, int]:
        return (self.x, self.y, self.z)

    def __getitem__(self, i: int) -> int:
        return (self.x, self.y, self.z)[i]

    def replace(self, axis: int, value: int) -> "Dim3":
        vals = [self.x, self.y, self.z]
        vals[axis] = value
        return Dim3(*vals)

    # --- arithmetic -----------------------------------------------------------
    def _coerce(self, o) -> "Dim3":
        return Dim3.of(o)

    def __add__(self, o) -> "Dim3":
        o = self._coerce(o)
        return Dim3(self.x + o.x, self.y + o.y, self.z + o.z)

    __radd__ = __add__

    def __sub__(self, o) -> "Dim3":
        o = self._coerce(o)
        return Dim3(self.x - o.x, self.y - o.y, self.z - o.z)

    def __rsub__(self, o) -> "Dim3":
        return self._coerce(o).__sub__(self)

    def __mul__(self, o) -> "Dim3":
        o = self._coerce(o)
        return Dim3(self.x * o.x, self.y * o.y, self.z * o.z)

    __rmul__ = __mul__

    def __floordiv__(self, o) -> "Dim3":
        o = self._coerce(o)
        return Dim3(self.x // o.x, self.y // o.y, self.z // o.z)

    def __mod__(self, o) -> "Dim3":
        o = self._coerce(o)
        return Dim3(self.x % o.x, self.y % o.y, self.z % o.z)

    def __neg__(self) -> "Dim3":
        return Dim3(-self.x, -self.y, -self.z)

    # --- ordering: x most significant (dim3.hpp:78-92) ------------------------
    def _key(self):
        return (self.x, self.y, self.z)

    def __lt__(self, o: "Dim3") -> bool:
        return self._key() < o._key()

    def __le__(self, o: "Dim3") -> bool:
        return self._key() <= o._key()

    def __gt__(self, o: "Dim3") -> bool:
        return self._key() > o._key()

    def __ge__(self, o: "Dim3") -> bool:
        return self._key() >= o._key()

    # --- predicates -----------------------------------------------------------
    def any_lt(self, v: int) -> bool:
        return self.x < v or self.y < v or self.z < v

    def any_gt(self, v: int) -> bool:
        return self.x > v or self.y > v or self.z > v

    def all_lt(self, v: int) -> bool:
        return self.x < v and self.y < v and self.z < v

    def all_gt(self, v: int) -> bool:
        return self.x > v and self.y > v and self.z > v

    def all_ge(self, v: int) -> bool:
        return self.x >= v and self.y >= v and self.z >= v

    # --- geometry -------------------------------------------------------------
    def flatten(self) -> int:
        """Number of points in a box of this extent (dim3.hpp:76)."""
        return self.x * self.y * self.z

    def wrap(self, lims: "Dim3") -> "Dim3":
        """Periodic wrap into [0, lims) (dim3.hpp:216-231).

        Like the reference, handles one period of out-of-range on either side
        (the only case halo neighbor math produces).
        """
        lims = Dim3.of(lims)
        return Dim3(
            (self.x + lims.x) % lims.x,
            (self.y + lims.y) % lims.y,
            (self.z + lims.z) % lims.z,
        )

    # --- misc -----------------------------------------------------------------
    @staticmethod
    def next_power_of_two(v: int) -> int:
        """dim3.hpp:13-21."""
        if v <= 0:
            return 0 if v == 0 else v
        return 1 << max(0, (v - 1).bit_length())

    def __repr__(self) -> str:
        return f"[{self.x},{self.y},{self.z}]"


@dataclasses.dataclass(frozen=True)
class Rect3:
    """Half-open axis-aligned box [lo, hi) (reference rect3.hpp:13-27)."""

    lo: Dim3
    hi: Dim3

    def __post_init__(self):
        object.__setattr__(self, "lo", Dim3.of(self.lo))
        object.__setattr__(self, "hi", Dim3.of(self.hi))

    def extent(self) -> Dim3:
        return self.hi - self.lo

    def contains(self, p: Dim3) -> bool:
        return (
            self.lo.x <= p.x < self.hi.x
            and self.lo.y <= p.y < self.hi.y
            and self.lo.z <= p.z < self.hi.z
        )

    def points(self):
        """Iterate all integer points, z-major (matches reference loop nests)."""
        for z in range(self.lo.z, self.hi.z):
            for y in range(self.lo.y, self.hi.y):
                for x in range(self.lo.x, self.hi.x):
                    yield Dim3(x, y, z)

    def __repr__(self) -> str:
        return f"Rect3({self.lo}..{self.hi})"


def euclid_dist(a: Dim3, b: Dim3) -> int:
    """Integer-truncated Euclidean distance (jacobi3d.cu:31-33 ``dist``)."""
    d = a - b
    return int(math.sqrt(float(d.x * d.x + d.y * d.y + d.z * d.z)))
