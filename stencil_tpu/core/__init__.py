"""Foundation geometry types (reference layer L0, ``include/stencil/``).

Pure Python, no JAX dependency — importable everywhere, including host-side
planning code and unit tests that never touch a device.
"""

from stencil_tpu.core.dim3 import Dim3, Rect3
from stencil_tpu.core.direction_map import DirectionMap, DIRECTIONS_26
from stencil_tpu.core.radius import Radius
from stencil_tpu.core.geometry import LocalSpec
from stencil_tpu.core.accessor import Accessor

__all__ = ["Dim3", "Rect3", "DirectionMap", "DIRECTIONS_26", "Radius", "LocalSpec", "Accessor"]
