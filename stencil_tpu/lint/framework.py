"""stencil-lint core: rule registry, suppression grammar, file engine.

The reference C++ library machine-checked its invariants with compile-time
types and mandatory error macros (``CUDA_RUNTIME`` / ``NVML``,
cuda_runtime.hpp:15); a Python port has neither, so the invariants PRs 1-3
established — validated env reads, jax-free telemetry, donated-buffer
safety, the PERF_NOTES layout traps, the tier-1 time budget — lived in
reviewer memory plus two one-off scripts.  This package turns each of them
into a registered :class:`Rule` over the stdlib ``ast``, with one entry
point (``python -m stencil_tpu.lint``) and one in-process tier-1 test.

Design constraints:

* **No jax, no third-party imports** — the linter must run in milliseconds
  in any interpreter (pre-commit, CI collection, the tier-1 gate).
* **Suppressions require a reason.**  A ``stencil-lint`` comment of the
  form ``disable=<rule> <why>`` on the flagged line (or the line directly
  above) silences that rule there;
  a bare ``disable=`` with no reason is itself a violation, and so is a
  suppression that no longer matches anything (allowlists must not rot —
  the same policy the old ``check_env_reads.ALLOWED`` set enforced).
* **Rules are data**: id, rationale, scope predicate, per-file ``check``,
  optional whole-run ``finalize`` for cross-file consistency checks.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import subprocess
import sys
import tokenize
from typing import Iterable, List, Optional, Sequence

#: repo root = the directory holding the ``stencil_tpu`` package
REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: rule id used for problems with the suppression comments themselves
SUPPRESSION_RULE = "bad-suppression"

#: rule id used for files the engine cannot parse at all
SYNTAX_RULE = "syntax-error"

_SUPPRESS_RE = re.compile(
    r"#\s*stencil-lint:\s*disable=([A-Za-z0-9_,-]+)[ \t]*(.*?)\s*$"
)


@dataclasses.dataclass(frozen=True)
class Violation:
    """One finding: rule id, repo-relative path, 1-based line, message."""

    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Suppression:
    line: int  # line the comment sits on
    rules: tuple  # rule ids named in disable=
    reason: str
    end: int = 0  # last covered line (>= line + 1 once resolved)

    def covers(self, line: int) -> bool:
        """A suppression covers its own line through ``end``: the line
        directly below, extended by the engine over the full span of the
        statement starting there (so a comment above a wrapped call covers
        every continuation line; compound statements extend only over
        their header, never the whole body)."""
        return self.line <= line <= max(self.end, self.line + 1)


class FileContext:
    """Parsed source handed to every rule: path, repo-relative path, text,
    AST (``None`` when the file does not parse), and raw lines."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        try:
            self.tree: Optional[ast.Module] = ast.parse(source, filename=path)
            self.syntax_error: Optional[SyntaxError] = None
        except SyntaxError as e:  # a broken file is its author's failure
            self.tree = None
            self.syntax_error = e
        self.suppressions: List[Suppression] = _resolve_spans(
            _parse_suppressions(source), self.tree, self.lines
        )

    def violation(self, rule: str, node_or_line, message: str) -> Violation:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Violation(rule=rule, path=self.rel, line=line, message=message)


class Rule:
    """Base class: subclass, set ``name``/``why``, implement ``check``.

    ``name`` is the id used in output and in ``disable=`` comments.
    ``why`` is the one-line rationale (``--list-rules``, docs catalog).
    ``applies_to(rel)`` scopes the rule to part of the tree; the engine
    only calls ``check`` on files inside that scope.  ``finalize()`` runs
    once per lint run for cross-file consistency checks (e.g. the
    telemetry registry's own well-formedness).
    """

    name: str = ""
    why: str = ""

    def applies_to(self, rel: str) -> bool:
        return True

    def check(self, ctx: FileContext) -> List[Violation]:
        raise NotImplementedError

    def finalize(self) -> List[Violation]:
        return []


#: the global registry, populated by the ``@register`` decorator at
#: ``stencil_tpu.lint.rules`` import time
_REGISTRY: List[type] = []


def register(cls: type) -> type:
    assert cls.name, f"{cls.__name__} must set a rule name"
    assert cls.name != SUPPRESSION_RULE, "reserved rule id"
    assert all(cls.name != c.name for c in _REGISTRY), f"duplicate rule {cls.name}"
    _REGISTRY.append(cls)
    return cls


def all_rules() -> List[type]:
    """Registered rule classes (importing the rules package on demand)."""
    from stencil_tpu.lint import rules as _rules  # noqa: F401  (registers)

    return list(_REGISTRY)


def _parse_suppressions(source: str) -> List[Suppression]:
    """Suppressions from real COMMENT tokens only — a string literal or
    docstring that merely quotes the syntax is not a suppression."""
    out = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out  # unparseable files are reported by the engine anyway
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if m:
            rules = tuple(r for r in m.group(1).split(",") if r)
            out.append(
                Suppression(line=tok.start[0], rules=rules, reason=m.group(2))
            )
    return out


def _resolve_spans(
    suppressions: List[Suppression], tree, lines: Sequence[str]
) -> List[Suppression]:
    """Extend each STANDALONE suppression comment over the statement that
    starts on the next line: wrapped calls anchor violations on
    continuation lines, and decorated defs anchor below their decorators.
    Compound statements (def/if/for/...) extend only over their header —
    a suppression never silences a whole body."""
    if tree is None or not suppressions:
        return suppressions
    span_by_start = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        start = node.lineno
        for d in getattr(node, "decorator_list", []):
            start = min(start, d.lineno)
        body = getattr(node, "body", None)
        if isinstance(body, list) and body:
            end = body[0].lineno - 1  # header only
        else:
            end = node.end_lineno or node.lineno
        span_by_start[start] = max(span_by_start.get(start, start), end)
    out = []
    for s in suppressions:
        standalone = (
            s.line <= len(lines) and lines[s.line - 1].lstrip().startswith("#")
        )
        end = span_by_start.get(s.line + 1, s.line + 1) if standalone else 0
        out.append(dataclasses.replace(s, end=end))
    return out


# --- engine -----------------------------------------------------------------


def _select_rules(select: Optional[Iterable[str]]) -> List[Rule]:
    classes = all_rules()
    if select is not None:
        wanted = set(select)
        known = {c.name for c in classes}
        unknown = wanted - known
        if unknown:
            raise ValueError(
                f"unknown rule(s) {sorted(unknown)}; known: {sorted(known)}"
            )
        classes = [c for c in classes if c.name in wanted]
    return [c() for c in classes]


def _apply_suppressions(
    ctx: FileContext, raw: List[Violation], active_rules: Iterable[str]
) -> List[Violation]:
    """Drop suppressed violations; emit bad-suppression findings for bare,
    unknown-rule, and unused suppression comments."""
    active = set(active_rules)
    out = []
    used = set()  # Suppression objects that silenced something
    for v in raw:
        silencer = None
        for s in ctx.suppressions:
            if v.rule in s.rules and s.covers(v.line) and s.reason:
                silencer = s
                break
        if silencer is not None:
            used.add(silencer.line)
        else:
            out.append(v)
    known = {c.name for c in all_rules()}
    for s in ctx.suppressions:
        if not s.reason:
            out.append(
                ctx.violation(
                    SUPPRESSION_RULE,
                    s.line,
                    "suppression without a reason — append why this site "
                    "is safe after the rule id",
                )
            )
            continue
        unknown = [r for r in s.rules if r not in known]
        if unknown:
            out.append(
                ctx.violation(
                    SUPPRESSION_RULE,
                    s.line,
                    f"suppression names unknown rule(s) {unknown}; known: "
                    f"{sorted(known)}",
                )
            )
            continue
        # rot check: a suppression whose rules all ran yet silenced nothing
        # no longer matches a violation and must be removed
        if (
            s.line not in used
            and all(r in active for r in s.rules)
        ):
            out.append(
                ctx.violation(
                    SUPPRESSION_RULE,
                    s.line,
                    f"unused suppression for {','.join(s.rules)} — the "
                    "violation it silenced is gone; remove the comment",
                )
            )
    return out


#: directories never linted (measurement probes, fixture corpora, caches)
EXCLUDED_DIRS = (
    os.path.join("scripts", "probes"),
    os.path.join("tests", "lint_fixtures"),
    os.path.join("tests", "analysis_fixtures"),
    "__pycache__",
)


def default_files(repo: str = REPO) -> List[str]:
    """The checked surface: the product tree plus its tests and the bench
    driver — ``stencil_tpu/``, ``tests/``, ``bench.py``, and the top-level
    ``scripts/*.py`` shims.  ``scripts/probes/`` (one-off measurement
    scripts) and the seeded-violation fixture corpus are out of scope."""
    out = []
    for root in ("stencil_tpu", "tests"):
        for dirpath, dirnames, files in os.walk(os.path.join(repo, root)):
            dirnames[:] = sorted(
                d
                for d in dirnames
                if not _excluded(os.path.relpath(os.path.join(dirpath, d), repo))
            )
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    scripts = os.path.join(repo, "scripts")
    if os.path.isdir(scripts):
        for f in sorted(os.listdir(scripts)):
            if f.endswith(".py"):
                out.append(os.path.join(scripts, f))
    bench = os.path.join(repo, "bench.py")
    if os.path.exists(bench):
        out.append(bench)
    return out


def _excluded(rel: str) -> bool:
    parts = rel.split(os.sep)
    if "__pycache__" in parts:
        return True
    for ex in EXCLUDED_DIRS:
        exp = ex.split(os.sep)
        if len(exp) > 1 and parts[: len(exp)] == exp:
            return True
    return False


def changed_files(repo: str = REPO) -> List[str]:
    """Files changed vs HEAD plus untracked files (for ``--changed-only``
    pre-commit runs).  Falls back to the full surface when git is absent."""
    try:
        diff = subprocess.run(
            ["git", "-C", repo, "diff", "--name-only", "HEAD", "--"],
            capture_output=True,
            text=True,
            timeout=30,
            check=True,
        ).stdout.splitlines()
        untracked = subprocess.run(
            ["git", "-C", repo, "ls-files", "--others", "--exclude-standard"],
            capture_output=True,
            text=True,
            timeout=30,
            check=True,
        ).stdout.splitlines()
    except (OSError, subprocess.SubprocessError):
        return default_files(repo)
    names = {n.strip() for n in diff + untracked if n.strip().endswith(".py")}
    return [p for p in default_files(repo) if os.path.relpath(p, repo) in names]


def lint_paths(
    paths: Sequence[str],
    select: Optional[Iterable[str]] = None,
    repo: str = REPO,
) -> List[Violation]:
    """Lint explicit files.  Returns all violations, sorted by location."""
    rules = _select_rules(select)
    active = [r.name for r in rules]
    out: List[Violation] = []
    for path in paths:
        rel = os.path.relpath(os.path.abspath(path), repo)
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        out.extend(_lint_one(FileContext(path, rel, source), rules, active))
    for r in rules:
        out.extend(r.finalize())
    return sorted(out, key=lambda v: (v.path, v.line, v.rule))


def lint_source(
    source: str,
    rel: str,
    select: Optional[Iterable[str]] = None,
) -> List[Violation]:
    """Lint an in-memory snippet as if it lived at repo-relative ``rel`` —
    the fixture-corpus entry point (rules scope themselves by path, so the
    caller picks which tree location the snippet impersonates)."""
    rules = _select_rules(select)
    active = [r.name for r in rules]
    out = _lint_one(FileContext("<fixture>", rel, source), rules, active)
    for r in rules:
        out.extend(r.finalize())
    return sorted(out, key=lambda v: (v.path, v.line, v.rule))


def _lint_one(
    ctx: FileContext, rules: List[Rule], active: List[str]
) -> List[Violation]:
    raw: List[Violation] = []
    applicable = [r for r in rules if r.applies_to(ctx.rel)]
    if ctx.tree is None:
        if applicable:
            raw.append(
                ctx.violation(
                    SYNTAX_RULE,
                    ctx.syntax_error.lineno or 1,
                    f"file does not parse: {ctx.syntax_error.msg}",
                )
            )
        return raw
    for r in applicable:
        raw.extend(r.check(ctx))
    return _apply_suppressions(ctx, raw, [r.name for r in applicable])


def run_lint(
    paths: Optional[Sequence[str]] = None,
    select: Optional[Iterable[str]] = None,
    changed_only: bool = False,
    repo: str = REPO,
) -> List[Violation]:
    """Lint the default surface (or explicit ``paths``).  The tier-1 test
    and the CLI both come through here."""
    if paths:
        files = list(paths)
    elif changed_only:
        files = changed_files(repo)
    else:
        files = default_files(repo)
    return lint_paths(files, select=select, repo=repo)


def render_json(violations: List[Violation], files_checked: int) -> str:
    return json.dumps(
        {
            "violations": [v.as_json() for v in violations],
            "count": len(violations),
            "files_checked": files_checked,
            "rules": sorted(c.name for c in all_rules()),
        },
        indent=2,
        sort_keys=True,
    )


def render_human(violations: List[Violation], stream=None) -> None:
    stream = stream or sys.stderr
    for v in violations:
        print(v.render(), file=stream)
    if violations:
        print(f"{len(violations)} stencil-lint problem(s)", file=stream)
