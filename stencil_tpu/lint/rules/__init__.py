"""Rule modules — importing this package registers every rule.

Adding a rule: write a module here with a ``@register``-decorated
:class:`stencil_tpu.lint.Rule` subclass, import it below, document it in
``docs/static-analysis.md``, and seed a fixture pair in
``tests/lint_fixtures/`` proving it fires and can be suppressed.
"""

from stencil_tpu.lint.rules import (  # noqa: F401
    accum_dtype,
    artifact_write,
    contract_coverage,
    donation,
    env_reads,
    jax_free,
    kernel_ledger,
    layout_traps,
    serve_invariants,
    span_name,
    telemetry_names,
    tier1_budget,
)
