"""Rules ``halo-set-in-loop`` and ``sliver-dus``: the PERF_NOTES layout
traps, as checkable patterns.

Measured on v5e (PERF_NOTES "Layout assignment traps" / "In-loop
aliased-pallas chains"):

* A ``.at[...].set`` halo write inside a ``fori_loop``/``scan`` body makes
  XLA materialize full-domain copy+DUS fusions per iteration (probe12) —
  the tile-local blend kernels in ``ops/halo_blend.py`` keep the chain
  in-place.  ``halo-set-in-loop`` flags ``.at[...].set`` reachable from a
  loop-body callable (lexically inside it, or in a same-file function the
  body calls by name — best-effort, bounded-depth).
* A y- or z-sliver ``dynamic_update_slice`` baits layout assignment into
  transposing the WHOLE array ({2,1,0}->{2,0,1} relayout copies, 9.2 ms
  per exchange at 518³ — probe6).  Whether a given DUS is a sliver is not
  statically decidable, so ``sliver-dus`` flags every
  ``dynamic_update_slice`` in the fast-path tree and asks the author to
  either switch to a blend kernel or suppress with the reason the site is
  contiguous/full-extent.

``ops/halo_blend.py`` itself is exempt — it IS the sanctioned fix and its
docstrings narrate the trap.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from stencil_tpu.lint import astutil
from stencil_tpu.lint.framework import FileContext, Rule, register

#: call-graph hops followed from a loop body when hunting .at[].set —
#: bounded so a by-name resolution mistake cannot spider the whole file
MAX_DEPTH = 4

_EXEMPT = "stencil_tpu/ops/halo_blend.py"


def _loop_body_roots(tree: ast.Module) -> List[ast.AST]:
    """The callables passed as bodies to ``fori_loop``/``scan``/
    ``while_loop``: lambda nodes directly, or same-file defs resolved by
    bare name."""
    defs = astutil.module_defs(tree)
    roots: List[ast.AST] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        cn = astutil.call_name(node)
        if cn == "fori_loop":
            cands = node.args[2:3]
        elif cn == "scan":
            cands = node.args[0:1]
        elif cn == "while_loop":
            cands = node.args[1:2]
        else:
            continue
        kw = astutil.keyword(node, "body_fun") or astutil.keyword(node, "f")
        if kw is not None:
            cands = [kw]
        for cand in cands:
            if isinstance(cand, ast.Lambda):
                roots.append(cand)
            elif isinstance(cand, ast.Name):
                roots.extend(defs.get(cand.id, []))
    return roots


def _reachable(roots: List[ast.AST], defs: Dict[str, List[ast.AST]]):
    """Functions reachable from the loop bodies by same-file bare-name
    calls (including functions passed onward as bare-name arguments),
    depth-bounded."""
    seen: Set[int] = set()
    frontier = [(r, 0) for r in roots]
    out = []
    while frontier:
        node, depth = frontier.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        out.append(node)
        if depth >= MAX_DEPTH:
            continue
        for name in astutil.called_names(node):
            for d in defs.get(name, []):
                if id(d) not in seen:
                    frontier.append((d, depth + 1))
    return out


@register
class HaloSetInLoopRule(Rule):
    name = "halo-set-in-loop"
    why = (
        "`.at[...].set` halo writes inside fori_loop/scan bodies "
        "materialize full-domain copy+DUS fusions every iteration; use the "
        "aliased blend kernels in ops/halo_blend.py (PERF_NOTES probe12)"
    )

    def applies_to(self, rel: str) -> bool:
        rel = rel.replace("\\", "/")
        return rel.startswith("stencil_tpu/") and rel != _EXEMPT

    def check(self, ctx: FileContext) -> List:
        roots = _loop_body_roots(ctx.tree)
        if not roots:
            return []
        defs = astutil.module_defs(ctx.tree)
        out = []
        seen_lines: Set[int] = set()
        for fn in _reachable(roots, defs):
            for node in ast.walk(fn):
                if astutil.is_at_set_call(node) and node.lineno not in seen_lines:
                    seen_lines.add(node.lineno)
                    out.append(
                        ctx.violation(
                            self.name,
                            node,
                            ".at[...].set inside (or reachable from) a "
                            "fori_loop/scan body — XLA materializes a "
                            "full-domain copy+DUS fusion per iteration; "
                            "write halos through the aliased kernels in "
                            "ops/halo_blend.py, or suppress with the "
                            "reason this buffer is small/off the fast "
                            "path (PERF_NOTES: layout assignment traps)",
                        )
                    )
        return out


@register
class SliverDusRule(Rule):
    name = "sliver-dus"
    why = (
        "a y/z-sliver dynamic_update_slice makes XLA transpose the whole "
        "array (9.2 ms/exchange at 518³, probe6); use ops/halo_blend.py "
        "or state why the update is contiguous"
    )

    def applies_to(self, rel: str) -> bool:
        rel = rel.replace("\\", "/")
        return rel.startswith("stencil_tpu/") and rel != _EXEMPT

    def check(self, ctx: FileContext) -> List:
        out = []
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Call)
                and astutil.call_name(node) == "dynamic_update_slice"
            ):
                out.append(
                    ctx.violation(
                        self.name,
                        node,
                        "dynamic_update_slice on the fast-path tree — a "
                        "y/z-sliver update baits XLA layout assignment "
                        "into relayout-copying the whole array; use the "
                        "tile-local kernels in ops/halo_blend.py, or "
                        "suppress stating why this update is contiguous "
                        "(x-plane / full-extent) (PERF_NOTES probe6)",
                    )
                )
        return out
