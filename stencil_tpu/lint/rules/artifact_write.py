"""Rule ``artifact-write``: durable run artifacts are written ATOMICALLY.

Checkpoints, tuned-config cache entries, bench/metrics JSON, weak-scaling
sweeps, plan dumps — a run artifact written with a bare ``open(path, "w")``
is a truncated half-file the moment the process is preempted mid-write,
and the long-run survival layer (docs/resilience.md "Long-run operation")
exists precisely because processes die mid-anything.  Every such write
goes through ``stencil_tpu/utils/artifact.py`` (``atomic_write`` /
``atomic_write_json`` / ``atomic_write_text``: same-directory temp file,
fsync, ``os.replace``), so the destination either keeps its old content or
atomically becomes the new content.

The rule flags ``open``/``io.open``/``os.fdopen`` calls whose mode creates
or truncates (``w``/``x`` modes).  Out of scope by design:

* append-mode streams (``"a"``) — the JSONL event sink's per-line append
  IS its crash contract (every line a complete document);
* reads and read-modify (``"r"``, ``"r+"``);
* ``tests/`` (tmp-path scratch is not an artifact) and the helper module
  itself (it is the sanctioned ``open`` site).

A non-artifact write (a fixture generator, a deliberately streaming file)
suppresses with a reason, as always.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from stencil_tpu.lint.framework import FileContext, Rule, Violation, register

#: the one module whose open() IS the atomic implementation
HELPER_MODULE = "stencil_tpu/utils/artifact.py"

_OPEN_NAMES = {"open"}
_OPEN_ATTRS = {("io", "open"), ("os", "fdopen")}


def _open_mode(node: ast.Call) -> Optional[str]:
    """The literal mode of an open-like call, or None (defaults to 'r',
    which never truncates)."""
    f = node.func
    is_open = (isinstance(f, ast.Name) and f.id in _OPEN_NAMES) or (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Name)
        and (f.value.id, f.attr) in _OPEN_ATTRS
    )
    if not is_open:
        return None
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) and isinstance(
            kw.value.value, str
        ):
            return kw.value.value
    if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant) and isinstance(
        node.args[1].value, str
    ):
        return node.args[1].value
    return "r" if (node.args or node.keywords) else None


@register
class ArtifactWriteRule(Rule):
    name = "artifact-write"
    why = (
        "bare open(path, 'w') leaves a truncated artifact when the process "
        "dies mid-write; route run artifacts through utils/artifact.py's "
        "atomic_write helpers (temp + fsync + rename)"
    )

    def applies_to(self, rel: str) -> bool:
        rel = rel.replace("\\", "/")
        if rel == HELPER_MODULE:
            return False
        return (
            rel.startswith("stencil_tpu/")
            or rel.startswith("scripts/")
            or rel == "bench.py"
        )

    def check(self, ctx: FileContext) -> List[Violation]:
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            mode = _open_mode(node)
            if mode is None or not any(c in mode for c in "wx"):
                # 'r'/'r+' never truncate; 'a' streams are the JSONL
                # contract (module docstring); only create/truncate modes
                # can shear an artifact
                continue
            out.append(
                ctx.violation(
                    self.name,
                    node,
                    f"bare open(..., {mode!r}) write — a kill mid-write "
                    "leaves a truncated artifact; use atomic_write/"
                    "atomic_write_json from stencil_tpu/utils/artifact.py "
                    "(or suppress with the reason this file is not a run "
                    "artifact)",
                )
            )
        return out
