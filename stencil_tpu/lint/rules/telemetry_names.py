"""Rule ``telemetry-name``: every telemetry metric/event name used in the
tree is registered in the canonical names module
(``stencil_tpu/telemetry/names.py``).

Two checks, over ``stencil_tpu/`` (telemetry internals exempt — they pass
names through as parameters), ``tests/``, and ``bench.py``:

1. A telemetry API call (``telemetry.inc`` / ``observe`` / ``set_gauge`` /
   ``emit_event`` / ``span`` / ``record_span`` / ``counter`` / ``gauge`` /
   ``histogram``) whose first argument is a STRING LITERAL must use a
   literal registered in ``names.ALL_NAMES`` — a free string silently
   forks the time series across bench rounds.
2. An attribute reference ``names.X`` / ``tm.X`` (the aliases this tree
   imports the module under) must name an existing constant — a typo'd
   constant would otherwise surface only at runtime on the telemetry path.

``finalize`` re-checks the registry itself: names are lowercase dotted
paths and no two constants share a value.
"""

from __future__ import annotations

import ast
from typing import List

from stencil_tpu.lint.framework import FileContext, Rule, Violation, register

#: telemetry facade entry points whose first positional arg is a series name
NAME_TAKING_CALLS = {
    "inc",
    "observe",
    "set_gauge",
    "emit_event",
    "span",
    "record_span",
    "counter",
    "gauge",
    "histogram",
}

#: module aliases the tree uses for the telemetry facade and the names module
FACADE_ALIASES = {"telemetry"}
NAMES_ALIASES = {"names", "tm"}


def _registry():
    """names.ALL_NAMES plus the constant map — imported lazily so the lint
    package stays importable even mid-refactor of the telemetry package.

    ``constants`` holds every uppercase module attribute: plain string names
    AND the keyed registries over them (``EXCHANGE_HOP_BYTES``,
    ``EXCHANGE_DIRECTION_SPANS`` — dicts mapping (axis, side) to a
    registered name).  The existence check accepts both; the hygiene checks
    in ``finalize`` apply only to the string-valued ones."""
    from stencil_tpu.telemetry import names

    constants = {k: v for k, v in vars(names).items() if k.isupper()}
    return names.ALL_NAMES, constants


def _is_telemetry_call(node: ast.Call) -> bool:
    """``telemetry.<api>(...)`` or a bare ``<api>(...)`` name imported from
    the facade — bare names are matched by name alone, which is safe because
    the API verbs are distinctive (``emit_event``, ``record_span``, ...) and
    a false positive only ever asks the author to register a name."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return (
            isinstance(f.value, ast.Name)
            and f.value.id in FACADE_ALIASES
            and f.attr in NAME_TAKING_CALLS
        )
    if isinstance(f, ast.Name):
        # bare imports: only the unambiguous verbs (plain `span`/`counter`
        # etc. collide with too many local names to match blindly)
        return f.id in {"emit_event", "record_span", "set_gauge"}
    return False


@register
class TelemetryNameRule(Rule):
    name = "telemetry-name"
    why = (
        "free-string telemetry names fork the cross-round time series; "
        "register every series in stencil_tpu/telemetry/names.py and "
        "reference the constant"
    )

    def applies_to(self, rel: str) -> bool:
        rel = rel.replace("\\", "/")
        if rel.startswith("stencil_tpu/telemetry/"):
            return False  # internals pass names through as parameters
        return (
            rel.startswith("stencil_tpu/")
            or rel.startswith("tests/")
            or rel == "bench.py"
        )

    def check(self, ctx: FileContext) -> List[Violation]:
        all_names, constants = _registry()
        out = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and _is_telemetry_call(node):
                if (
                    node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    lit = node.args[0].value
                    if lit not in all_names:
                        out.append(
                            ctx.violation(
                                self.name,
                                node,
                                f"free-string telemetry name {lit!r} — "
                                "register it in stencil_tpu/telemetry/"
                                "names.py and reference the constant",
                            )
                        )
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id in NAMES_ALIASES
                and node.attr.isupper()
                and node.attr not in constants
                and not node.attr.startswith("ALL_")
            ):
                out.append(
                    ctx.violation(
                        self.name,
                        node,
                        f"names.{node.attr} is not defined in "
                        "stencil_tpu/telemetry/names.py",
                    )
                )
        return out

    def finalize(self) -> List[Violation]:
        _, constants = _registry()
        out = []
        seen = {}
        rel = "stencil_tpu/telemetry/names.py"
        for const, value in sorted(constants.items()):
            if not isinstance(value, str):
                continue  # keyed registries: their values are the constants
            if not all(part for part in value.split(".")) or value != value.lower():
                out.append(
                    Violation(
                        self.name,
                        rel,
                        1,
                        f"names.{const} = {value!r}: names are lowercase "
                        "dotted paths",
                    )
                )
            if value in seen:
                out.append(
                    Violation(
                        self.name,
                        rel,
                        1,
                        f"names.{const} duplicates names.{seen[value]} "
                        f"({value!r})",
                    )
                )
            seen[value] = const
        return out
