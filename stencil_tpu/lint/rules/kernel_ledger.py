"""Rule ``kernel-ledger``: a pallas kernel added under ``ops/`` must be
named in the kernel-coverage ledger (``PALLAS_KERNELS`` in
``stencil_tpu/analysis/registry.py``) — the ``contract-coverage`` pattern
one level down.

Why: the kernel verifier (``analysis/kernels.py``; contracts
``kernel-race``/``kernel-coverage``/``tiling-legal``,
docs/static-analysis.md "Kernel verifier") descends into every pallas call
the canonical matrix traces, but a NEW kernel the matrix never reaches is
an unverified write surface: its grid could race, its block maps could
leave output gaps, its shapes could be Mosaic-illegal — exactly the
failure classes the verifier exists to make static.  This rule fails the
defining module until the jax-free ledger — which
``tests/test_analysis.py::test_kernel_ledger_matches_tree`` pins against
the real tree in both directions — names every top-level function that
issues a ``pallas_call``.
"""

from __future__ import annotations

import ast
from typing import List

from stencil_tpu.lint.framework import FileContext, Rule, Violation, register


def _ledger():
    """The jax-free kernel ledger — imported lazily (the registry module
    never touches jax; the lint run stays milliseconds)."""
    from stencil_tpu.analysis.registry import PALLAS_KERNELS

    return PALLAS_KERNELS


def _issues_pallas_call(node: ast.FunctionDef) -> bool:
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        fn = sub.func
        if isinstance(fn, ast.Attribute) and fn.attr == "pallas_call":
            return True
        if isinstance(fn, ast.Name) and fn.id == "pallas_call":
            return True
    return False


@register
class KernelLedgerRule(Rule):
    name = "kernel-ledger"
    why = (
        "an ops/ function issuing a pallas_call must be named in the "
        "kernel-coverage ledger (analysis/registry.py PALLAS_KERNELS) — "
        "new kernels cannot ship outside the kernel verifier's sweep"
    )

    def applies_to(self, rel: str) -> bool:
        return rel.replace("\\", "/").startswith("stencil_tpu/ops/")

    def check(self, ctx: FileContext) -> List[Violation]:
        ledger = _ledger()
        rel = ctx.rel.replace("\\", "/")
        named = ledger.get(rel, ())
        out: List[Violation] = []
        for node in ctx.tree.body:  # top level only: helpers that build a
            # pallas_call for an enclosing kernel fn are that kernel's body
            if not isinstance(node, ast.FunctionDef):
                continue
            if not _issues_pallas_call(node):
                continue
            if node.name in named:
                continue
            out.append(
                ctx.violation(
                    self.name,
                    node,
                    f"{node.name} issues a pallas_call but is not in the "
                    f"kernel-coverage ledger for {rel} — add it to "
                    "PALLAS_KERNELS in stencil_tpu/analysis/registry.py "
                    "(and reach it from the canonical matrix or the "
                    "fixture corpus) before shipping the kernel",
                )
            )
        return out
