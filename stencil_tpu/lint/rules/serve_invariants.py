"""Rule ``bounded-queue``: no unbounded buffering in the serving layer.

The serving layer's overload contract (docs/serving.md "Shedding policy")
is that load past capacity becomes a CLASSIFIED, retryable refusal at the
edge — never silent queue growth.  An unbounded queue converts overload
into latency collapse and OOM: every request "succeeds" into a buffer
whose wait time is already past any deadline, and the process dies of
memory instead of shedding.  The invariant is structural, so it lints:

* ``collections.deque(...)`` (or bare ``deque(...)``) without a ``maxlen``
  keyword is flagged — a deque WITH ``maxlen`` is bounded by construction;
* ``queue.Queue(...)`` / ``queue.SimpleQueue()`` (and the
  ``LifoQueue``/``PriorityQueue`` variants) without a positive ``maxsize``
  are flagged — ``Queue()``'s default ``maxsize=0`` means unbounded.

Scope: ``stencil_tpu/serve/`` only.  Elsewhere a deque is a scratch
structure bounded by its producer (e.g. the telemetry event ring caps
itself); inside the serving layer every buffer sits on the request path,
where "the producer bounds it" is exactly the assumption overload breaks.
A deliberately unbounded serve-side structure suppresses with a reason,
as always.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from stencil_tpu.lint.framework import FileContext, Rule, Violation, register

#: queue.* constructors whose default is unbounded
_QUEUE_CLASSES = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}


def _call_name(node: ast.Call) -> Optional[tuple]:
    """("deque", None) / ("queue", "Queue") style (module, attr) id for
    the constructors this rule audits, else None."""
    f = node.func
    if isinstance(f, ast.Name):
        if f.id == "deque":
            return ("collections", "deque")
        if f.id in _QUEUE_CLASSES:
            return ("queue", f.id)
        return None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        if f.value.id == "collections" and f.attr == "deque":
            return ("collections", "deque")
        if f.value.id == "queue" and f.attr in _QUEUE_CLASSES:
            return ("queue", f.attr)
    return None


def _bounded(node: ast.Call, kind: tuple) -> bool:
    if kind == ("collections", "deque"):
        # deque(iterable, maxlen) positionally, or maxlen= keyword; a
        # maxlen of literal None is unbounded by definition
        if len(node.args) >= 2:
            return not (
                isinstance(node.args[1], ast.Constant)
                and node.args[1].value is None
            )
        for kw in node.keywords:
            if kw.arg == "maxlen":
                return not (
                    isinstance(kw.value, ast.Constant) and kw.value.value is None
                )
        return False
    if kind[1] == "SimpleQueue":
        return False  # SimpleQueue has no maxsize at all
    # queue.Queue(maxsize) / maxsize= — the default 0 means unbounded, and
    # a literal 0 or negative spells it explicitly
    size = None
    if node.args:
        size = node.args[0]
    for kw in node.keywords:
        if kw.arg == "maxsize":
            size = kw.value
    if size is None:
        return False
    if isinstance(size, ast.Constant) and isinstance(size.value, (int, float)):
        return size.value > 0
    return True  # a computed bound: trust the expression names one


@register
class BoundedQueueRule(Rule):
    name = "bounded-queue"
    why = (
        "an unbounded queue in the serving layer turns overload into "
        "latency collapse + OOM instead of a classified refusal; construct "
        "deques with maxlen= and queue.Queue with a positive maxsize"
    )

    def applies_to(self, rel: str) -> bool:
        return rel.replace("\\", "/").startswith("stencil_tpu/serve/")

    def check(self, ctx: FileContext) -> List[Violation]:
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _call_name(node)
            if kind is None or _bounded(node, kind):
                continue
            ctor = ".".join(kind)
            out.append(
                ctx.violation(
                    self.name,
                    node,
                    f"unbounded {ctor}(...) on the request path — overload "
                    "must become a classified refusal at the edge, not "
                    "silent buffering; pass maxlen=/a positive maxsize (or "
                    "suppress with the reason this buffer is bounded by "
                    "construction elsewhere)",
                )
            )
        return out
