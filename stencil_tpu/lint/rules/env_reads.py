"""Rule ``env-read``: every ``STENCIL_*`` environment variable is read
through ``utils/config.py``'s validated helpers (``env_int`` / ``env_float``
/ ``env_bool`` / ``env_str`` / ``env_choice``), never via a raw
``os.environ`` / ``os.getenv`` at a call site.

Why: a raw read silently accepts malformed values (``"0 "`` vs ``"0"``,
``"16MB"`` vs bytes) and each site invents its own truthiness convention;
the validated helpers raise a message NAMING the variable at the read site
and keep one boolean vocabulary.  PR-1/PR-2 converted the tree; the old
``scripts/check_env_reads.py`` grandfather list (logging's import-time
level parse) is now an inline ``disable=env-read`` suppression at the
read itself, with the reason alongside the code it excuses.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from stencil_tpu.lint.framework import FileContext, Rule, Violation, register

_ENV_FUNCS = {"getenv"}  # os.getenv(...)
_OS_NAMES = {"os", "_os"}

#: the ONE module allowed to touch os.environ for STENCIL_* names
CONFIG_MODULE = "stencil_tpu/utils/config.py"


def env_read_var(node: ast.expr) -> Optional[str]:
    """The string literal read by this expression, or None.

    Matches ``os.environ.get(LIT, ...)``, ``os.environ[LIT]``,
    ``os.getenv(LIT, ...)``, and the bare-``environ`` forms from
    ``from os import environ``."""

    def _is_environ(expr: ast.expr) -> bool:
        if isinstance(expr, ast.Attribute) and expr.attr == "environ":
            return isinstance(expr.value, ast.Name) and expr.value.id in _OS_NAMES
        return isinstance(expr, ast.Name) and expr.id == "environ"

    def _lit(args):
        if args and isinstance(args[0], ast.Constant) and isinstance(args[0].value, str):
            return args[0].value
        return None

    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "get" and _is_environ(f.value):
            return _lit(node.args)
        if (
            isinstance(f, ast.Attribute)
            and f.attr in _ENV_FUNCS
            and isinstance(f.value, ast.Name)
            and f.value.id in _OS_NAMES
        ):
            return _lit(node.args)
    if isinstance(node, ast.Subscript) and _is_environ(node.value):
        sl = node.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            return sl.value
    return None


@register
class EnvReadRule(Rule):
    name = "env-read"
    why = (
        "raw os.environ reads of STENCIL_* knobs skip validation; use the "
        "env_* helpers in utils/config.py so malformed values fail naming "
        "the variable"
    )

    def applies_to(self, rel: str) -> bool:
        rel = rel.replace("\\", "/")
        if rel == CONFIG_MODULE:
            return False  # the one module allowed to touch os.environ
        return rel.startswith("stencil_tpu/") or rel == "bench.py"

    def check(self, ctx: FileContext) -> List[Violation]:
        out = []
        for node in ast.walk(ctx.tree):
            var = env_read_var(node)
            if var is None or not var.startswith("STENCIL_"):
                continue
            out.append(
                ctx.violation(
                    self.name,
                    node,
                    f"raw environment read of {var!r} — use a validated "
                    "helper from stencil_tpu/utils/config.py (env_int/"
                    "env_float/env_bool/env_str/env_choice) so malformed "
                    "values fail naming the variable",
                )
            )
        return out
