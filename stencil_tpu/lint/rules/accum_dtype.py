"""Rule ``accum-dtype``: every matrix contraction in ``stencil_tpu/ops/``
(``dot_general`` / ``jnp.dot`` / ``jnp.matmul`` / ``jnp.einsum``) passes an
explicit ``preferred_element_type``.

Why: the MXU offload (ops/jacobi_pallas ``band_matrix`` + the contraction
level kernels) exists precisely to run reduced-precision storage through
full-precision accumulation — a ``dot_general`` over bf16 operands WITHOUT
``preferred_element_type`` silently accumulates at bf16 (bf16x bf16 -> bf16),
which is exactly the bug class the bf16-storage/f32-accumulate contract
forbids (docs/tuning.md "Compute unit and storage dtype"; PERF_NOTES "VPU
wall").  Making the accumulator explicit at every contraction site keeps the
contract checkable instead of hoping each kernel author remembers the XLA
default.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from stencil_tpu.lint.framework import FileContext, Rule, Violation, register

#: callee attribute names that lower to an XLA dot (einsum included: it
#: takes the same keyword and has the same silent-bf16-accumulate default)
_DOT_FUNCS = {"dot_general", "dot", "matmul", "einsum"}

#: module aliases a contraction is expected to hang off — ``jnp.dot``,
#: ``lax.dot_general``, ``jax.lax.dot_general``, ``jax.numpy.matmul``...
_MODULE_NAMES = {"jnp", "lax", "jax", "numpy", "pl", "pltpu"}


def _dot_callee(node: ast.Call) -> Optional[str]:
    """The contraction function name when this call is one, else None.

    Matches ``<mod>.<fn>(...)`` for fn in ``_DOT_FUNCS`` with ``<mod>``
    rooted at a known module alias (``np.dot`` on host arrays is out of
    scope only by module name — ops/ kernels use jnp/lax), and the bare
    ``dot_general(...)`` form from ``from jax.lax import dot_general``."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _DOT_FUNCS:
        root = f.value
        while isinstance(root, ast.Attribute):
            root = root.value
        if isinstance(root, ast.Name) and root.id in _MODULE_NAMES:
            return f.attr
        return None
    if isinstance(f, ast.Name) and f.id in _DOT_FUNCS:
        return f.id
    return None


@register
class AccumDtypeRule(Rule):
    name = "accum-dtype"
    why = (
        "a dot_general/jnp.dot in ops/ without preferred_element_type "
        "silently accumulates bf16 x bf16 at bf16 — the accumulator must be "
        "explicit so the f32-accumulate contract is checkable"
    )

    def applies_to(self, rel: str) -> bool:
        rel = rel.replace("\\", "/")
        return rel.startswith("stencil_tpu/ops/")

    def check(self, ctx: FileContext) -> List[Violation]:
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _dot_callee(node)
            if fn is None:
                continue
            kw_names = {k.arg for k in node.keywords}
            if "preferred_element_type" in kw_names:
                continue
            if None in kw_names:
                continue  # a **kwargs splat may carry it; not statically decidable
            out.append(
                ctx.violation(
                    self.name,
                    node,
                    f"{fn}() without preferred_element_type — bf16 operands "
                    "would silently accumulate at bf16; pin the accumulator "
                    "(preferred_element_type=jnp.float32) per the "
                    "f32-accumulate contract (docs/tuning.md)",
                )
            )
        return out
