"""Rule ``slow-marker``: tier-1 time-budget discipline for tests.

ROADMAP records tier-1 clipping its 870 s timeout when heavyweight tests
landed unmarked; PR 3 had to evacuate two AOT proofs (481 s for one) to
tier-2 to restore headroom.  The expensive class is mechanical to spot: a
test that spawns a fresh interpreter (``sys.executable`` / ``subprocess``)
pays import+backend cold start per run, and a test that invokes
``bench.py`` runs a full measurement protocol.  Such tests must carry
``@pytest.mark.slow`` (tier-2) — or a suppression stating why the spawn is
cheap (e.g. logging's jax-free ``python -c`` children).

Detection is transitive over same-file helpers: a test calling a module
helper that spawns is as expensive as spawning inline.  Docstrings are
ignored (mentioning bench.py is not running it).
"""

from __future__ import annotations

import ast
from typing import List, Set

from stencil_tpu.lint import astutil
from stencil_tpu.lint.framework import FileContext, Rule, register

_SPAWN_ATTRS = {"executable"}  # sys.executable
_SUBPROCESS_CALLS = {"run", "Popen", "call", "check_call", "check_output"}


def _is_docstring(node: ast.AST, parents: Set[int]) -> bool:
    return id(node) in parents


def _docstring_constants(tree: ast.Module) -> Set[int]:
    """ids of every Constant that is a docstring expression."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        body = getattr(node, "body", None)
        if (
            isinstance(node, (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
            and body
            and isinstance(body[0], ast.Expr)
            and isinstance(body[0].value, ast.Constant)
            and isinstance(body[0].value.value, str)
        ):
            out.add(id(body[0].value))
    return out


def _spawns_directly(fn: ast.AST, docstrings: Set[int]) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute):
            if node.attr in _SPAWN_ATTRS and astutil.dotted(node) == "sys.executable":
                return True
            if (
                node.attr in _SUBPROCESS_CALLS
                and isinstance(node.value, ast.Name)
                and node.value.id == "subprocess"
            ):
                return True
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and "bench.py" in node.value
            and not _is_docstring(node, docstrings)
        ):
            return True
    return False


def _slow_marked(fn, klass, module_marks: bool) -> bool:
    def mark_in(dec_list) -> bool:
        for d in dec_list:
            target = d.func if isinstance(d, ast.Call) else d
            name = astutil.dotted(target) or ""
            if name.endswith("mark.slow") or name == "slow":
                return True
        return False

    if module_marks:
        return True
    if mark_in(fn.decorator_list):
        return True
    return klass is not None and mark_in(klass.decorator_list)


def _module_pytestmark_slow(tree: ast.Module) -> bool:
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "pytestmark" for t in node.targets
        ):
            for n in ast.walk(node.value):
                if isinstance(n, ast.Attribute) and n.attr == "slow":
                    return True
    return False


@register
class SlowMarkerRule(Rule):
    name = "slow-marker"
    why = (
        "tests that spawn interpreters or run bench.py pay cold starts the "
        "870s tier-1 budget cannot absorb; mark them @pytest.mark.slow or "
        "suppress stating why the spawn is cheap"
    )

    def applies_to(self, rel: str) -> bool:
        rel = rel.replace("\\", "/")
        return rel.startswith("tests/") and rel.split("/")[-1].startswith("test_")

    def check(self, ctx: FileContext) -> List:
        tree = ctx.tree
        docstrings = _docstring_constants(tree)
        defs = astutil.module_defs(tree)
        # transitive spawn set over same-file helpers (fixpoint)
        spawny: Set[str] = {
            name
            for name, nodes in defs.items()
            if any(_spawns_directly(n, docstrings) for n in nodes)
        }
        changed = True
        while changed:
            changed = False
            for name, nodes in defs.items():
                if name in spawny:
                    continue
                for n in nodes:
                    if astutil.called_names(n) & spawny:
                        spawny.add(name)
                        changed = True
                        break
        module_marks = _module_pytestmark_slow(tree)
        out = []
        for klass, fn in _test_functions(tree):
            if fn.name not in spawny:
                continue
            if _slow_marked(fn, klass, module_marks):
                continue
            # anchor at the first decorator so a suppression directly above
            # the decorated test covers the finding
            anchor = min([d.lineno for d in fn.decorator_list] + [fn.lineno])
            out.append(
                ctx.violation(
                    self.name,
                    anchor,
                    f"{fn.name} spawns a subprocess / runs bench.py but is "
                    "not @pytest.mark.slow — heavyweight tests go to "
                    "tier-2 (ROADMAP: tier-1 870s budget), or suppress "
                    "with the reason the child is cheap",
                )
            )
        return out


def _test_functions(tree: ast.Module):
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name.startswith("test"):
                yield None, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) and sub.name.startswith("test"):
                    yield node, sub
