"""Rule ``span-name``: span labels — ``annotate()`` named scopes and the
first argument of ``span()``/``record_span()`` — must be SPAN constants
from ``stencil_tpu/telemetry/names.py`` (``names.ALL_SPANS``).

The general ``telemetry-name`` rule already rejects names absent from the
registry; this rule closes the two gaps that matter for DEVICE-time
attribution (telemetry/device.py):

1. ``telemetry.annotate(...)`` was previously unchecked entirely — yet its
   labels are what land in compiled HLO metadata and XProf device rows, so
   a free-string scope silently falls out of the roofline attribution
   (``attribute_device_time`` matches registered scope names).
2. A span call naming a COUNTER or EVENT constant parses as "registered"
   under ``telemetry-name`` but forks the timeline kind: span literals
   must be spans specifically.
3. ``jax.named_scope(<string literal>)`` — the raw form the in-kernel
   exchange sweeps once used (``halo_ppermute_*`` f-strings).  Kernel
   scopes are device-timeline spans exactly like ``annotate`` labels, so
   a literal there must be a registered span too; non-literal arguments
   (the ``names.exchange_direction_span`` helper, SPAN_* constants) are
   the sanctioned form and pass through — the ``span-registry`` contract
   covers those at trace level.

Scope: the product tree (``stencil_tpu/``) and ``bench.py`` — telemetry
internals are exempt (they pass names through as parameters), and tests
may build synthetic spans.
"""

from __future__ import annotations

import ast
from typing import List

from stencil_tpu.lint.framework import FileContext, Rule, Violation, register

#: telemetry facade calls whose first positional arg is a SPAN label
SPAN_TAKING_CALLS = {"annotate", "span", "record_span"}

#: module aliases the tree uses for the telemetry facade
FACADE_ALIASES = {"telemetry"}


def _span_registry():
    """names.ALL_SPANS — imported lazily so the lint package stays
    importable mid-refactor of the telemetry package."""
    from stencil_tpu.telemetry import names

    return names.ALL_SPANS


def _is_span_call(node: ast.Call) -> bool:
    """``telemetry.annotate/span/record_span(...)``, a bare ``annotate(...)``
    (the one verb distinctive enough to match by name — plain ``span``
    collides with too many locals), or ``jax.named_scope(...)`` (in-kernel
    device-timeline scopes)."""
    f = node.func
    if isinstance(f, ast.Attribute):
        if not isinstance(f.value, ast.Name):
            return False
        if f.value.id in FACADE_ALIASES and f.attr in SPAN_TAKING_CALLS:
            return True
        return f.value.id == "jax" and f.attr == "named_scope"
    if isinstance(f, ast.Name):
        return f.id == "annotate"
    return False


@register
class SpanNameRule(Rule):
    name = "span-name"
    why = (
        "annotate()/span labels land in HLO metadata and the device-time "
        "attribution keys on them; use the SPAN constants from "
        "stencil_tpu/telemetry/names.py"
    )

    def applies_to(self, rel: str) -> bool:
        rel = rel.replace("\\", "/")
        if rel.startswith("stencil_tpu/telemetry/"):
            return False  # internals pass names through as parameters
        return rel.startswith("stencil_tpu/") or rel == "bench.py"

    def check(self, ctx: FileContext) -> List[Violation]:
        spans = _span_registry()
        out = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and _is_span_call(node)):
                continue
            if (
                node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                lit = node.args[0].value
                if lit not in spans:
                    out.append(
                        ctx.violation(
                            self.name,
                            node,
                            f"span label {lit!r} is not a registered span "
                            "— add a SPAN_* constant to stencil_tpu/"
                            "telemetry/names.py (ALL_SPANS) and reference "
                            "it, so device-time attribution can key on it",
                        )
                    )
        return out
