"""Rule ``donated-reuse``: a binding passed into a donating call
(``donate_argnums`` / ``input_output_aliases``) must not be read again
afterwards unless the surrounding code carries a liveness guard.

This is the bug class PR 1's runtime guard exists to catch: every fast-path
step is jitted with ``donate_argnums=0``, so after ``step(x)`` the buffer
behind ``x`` may already be freed — re-reading it raises (best case) or
re-runs on deleted memory on a retry path (worst case; see
``resilience/retry.py`` ``buffers_live``).  The lint flags the static shape
of the mistake: a call through a callable *known in this file* to donate
(its def is decorated ``partial(jax.jit, ..., donate_argnums=...)``, or the
name was bound to ``jax.jit(f, donate_argnums=...)`` /
``pallas_call(..., input_output_aliases=...)``), whose donated argument is
a bare name that is loaded again later in the same scope before any
rebinding of that name.

Not flagged (the sanctioned patterns):

* rebinding through the result — ``x = step(x)`` — later reads see the
  fresh buffer, and any rebinding of the name closes the hazard window;
* scopes that guard with ``is_deleted()`` / ``buffers_live`` or route the
  re-invocation through ``execute_with_retry`` (the runtime guard);
* donation through ``**kwargs``, attribute or subscript arguments — those
  are beyond by-name dataflow and stay the runtime guard's job.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from stencil_tpu.lint import astutil
from stencil_tpu.lint.framework import FileContext, Rule, register

#: names whose presence in a scope marks the reuse as liveness-guarded
GUARD_NAMES = {"is_deleted", "buffers_live", "execute_with_retry"}


def _donated_positions(call: ast.Call) -> Optional[Set[int]]:
    """Donated argument indices declared by this jit/pallas_call invocation,
    or None when it donates nothing."""
    kw = astutil.keyword(call, "donate_argnums")
    if kw is not None:
        return astutil.const_int_set(kw) or {0}
    kw = astutil.keyword(call, "input_output_aliases")
    if kw is not None:
        if isinstance(kw, ast.Dict):
            keys = set()
            for k in kw.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, int):
                    keys.add(k.value)
            return keys or {0}
        return {0}
    return None


def _donating_defs(tree: ast.Module) -> Dict[str, Set[int]]:
    """name -> donated positions, for every callable this file declares to
    donate: decorated defs and names assigned from a donating call."""
    out: Dict[str, Set[int]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    pos = _donated_positions(dec)
                    if pos:
                        out[node.name] = pos
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            pos = _donated_positions(node.value)
            if pos:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = pos
    return out


def _own_nodes(scope: ast.AST) -> List[ast.AST]:
    """Nodes belonging to this scope, excluding nested function/lambda
    subtrees (each of those is analyzed as its own scope)."""
    body = scope.body if isinstance(scope.body, list) else [scope.body]
    own: List[ast.AST] = []
    stack: List[ast.AST] = list(body)
    while stack:
        n = stack.pop()
        if isinstance(n, astutil.FUNC_NODES):
            continue  # nested scope, analyzed separately
        own.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return own


@register
class DonatedReuseRule(Rule):
    name = "donated-reuse"
    why = (
        "a buffer passed through donate_argnums/input_output_aliases may "
        "already be freed; rebind through the result (x = step(x)) or "
        "guard with is_deleted()/buffers_live before reusing it"
    )

    def applies_to(self, rel: str) -> bool:
        rel = rel.replace("\\", "/")
        return rel.startswith("stencil_tpu/") or rel == "bench.py"

    def check(self, ctx: FileContext) -> List:
        donating = _donating_defs(ctx.tree)
        if not donating:
            return []
        out = []
        for scope in astutil.function_scopes(ctx.tree):
            out.extend(self._check_scope(ctx, scope, donating))
        return out

    def _check_scope(self, ctx: FileContext, scope, donating) -> List:
        own = _own_nodes(scope)
        # a guarded scope (anywhere in its subtree, nested helpers included)
        # delegates liveness to the runtime check
        walk_root = scope.body if isinstance(scope.body, list) else [scope.body]
        for top in walk_root:
            for n in ast.walk(top):
                if isinstance(n, (ast.Name, ast.Attribute)):
                    nm = n.id if isinstance(n, ast.Name) else n.attr
                    if nm in GUARD_NAMES:
                        return []
        assigns = [
            n
            for n in own
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign))
        ]
        out = []
        for call in own:
            if not isinstance(call, ast.Call):
                continue
            fname = astutil.call_name(call)
            if fname not in donating:
                continue
            for idx in donating[fname]:
                if idx >= len(call.args):
                    continue
                arg = call.args[idx]
                if not isinstance(arg, ast.Name):
                    continue  # attribute/subscript: runtime guard's job
                if self._rebound_by_own_statement(assigns, call, arg.id):
                    continue  # x = step(x): reads see the fresh buffer
                reuse = self._first_event_after(walk_root, call, arg.id)
                if reuse is not None:
                    out.append(
                        ctx.violation(
                            self.name,
                            reuse,
                            f"{arg.id!r} was donated to {fname}() on line "
                            f"{call.lineno} and may be deleted — rebind "
                            "through the result or guard with is_deleted()"
                            "/buffers_live (see resilience/retry.py)",
                        )
                    )
        return out

    @staticmethod
    def _rebound_by_own_statement(assigns, call: ast.Call, name: str) -> bool:
        """True when the statement holding the donating call assigns the
        donated name — the canonical ``x = step(x)`` swap (incl. tuple
        targets), after which every read sees the fresh buffer."""
        for a in assigns:
            if any(sub is call for sub in ast.walk(a)):
                targets = a.targets if isinstance(a, ast.Assign) else [a.target]
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name) and n.id == name:
                            return True
        return False

    @staticmethod
    def _first_event_after(walk_root, call: ast.Call, name) -> Optional[ast.AST]:
        """The first Load of ``name`` after the donating call, or None when
        the name is rebound first (a Store closes the hazard window).
        Nested defs count as loads: a closure capturing the stale binding
        is just as dead.  Position comparison is (line, col) against the
        call's END so same-line reuse (``return step(x), x.shape``) is
        caught while the call's own argument is not."""
        end = (call.end_lineno or call.lineno, call.end_col_offset or 0)
        events = []
        for top in walk_root:
            for n in ast.walk(top):
                if (
                    isinstance(n, ast.Name)
                    and n.id == name
                    and (n.lineno, n.col_offset) > end
                ):
                    events.append(n)
        if not events:
            return None
        first = min(events, key=lambda n: (n.lineno, n.col_offset))
        if isinstance(first.ctx, ast.Load):
            return first
        return None
