"""Rule ``contract-coverage``: an ops/ module that grows a tuner-axis
vocabulary (``EXCHANGE_ROUTES``, ``STREAM_OVERLAP``, ``COMPUTE_UNITS``,
``STORAGE_DTYPES``) must grow the program-contract verifier's canonical
matrix with it.

Why: the analysis package (``python -m stencil_tpu.analysis``,
docs/static-analysis.md "Program contracts") machine-checks the traced-
program invariants — fused ≤6-permute exchanges, split-step independence,
thin-z relayout traps — against REAL built programs swept over the axis
vocabularies.  A new exchange route or overlap schedule that no canonical
program exercises is an unverified fast path: this rule fails the defining
module until the jax-free coverage ledger
(``stencil_tpu/analysis/registry.py``) — which
``tests/test_analysis.py::test_registry_matches_matrix`` pins against the
real matrix — names every declared value.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from stencil_tpu.lint.framework import FileContext, Rule, Violation, register


def _ledger():
    """The jax-free coverage ledger — imported lazily (the registry module
    never touches jax, so this stays milliseconds; the analysis package
    __init__ is import-light by contract)."""
    from stencil_tpu.analysis.registry import CANONICAL_AXES

    return CANONICAL_AXES


def _tuple_of_strs(node: ast.expr) -> Optional[List[str]]:
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    vals = []
    for el in node.elts:
        if not (isinstance(el, ast.Constant) and isinstance(el.value, str)):
            return None
        vals.append(el.value)
    return vals


@register
class ContractCoverageRule(Rule):
    name = "contract-coverage"
    why = (
        "an ops/ or serve/ module growing an axis vocabulary "
        "(EXCHANGE_ROUTES, STREAM_OVERLAP, SERVE_MODES, ...) must be named "
        "in the analysis canonical-matrix ledger — new routes cannot ship "
        "unverified by the program contracts"
    )

    def applies_to(self, rel: str) -> bool:
        rel = rel.replace("\\", "/")
        # serve/ carries one axis vocabulary too: pack.SERVE_MODES (the
        # packed-dispatch modes the batch-isolation contract sweeps)
        return rel.startswith(("stencil_tpu/ops/", "stencil_tpu/serve/"))

    def check(self, ctx: FileContext) -> List[Violation]:
        ledger = _ledger()
        out: List[Violation] = []
        rel = ctx.rel.replace("\\", "/")
        for node in ctx.tree.body:  # module level only: the axis tuples
            # are module constants by convention (tuner-axis vocabularies)
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            axis = next((n for n in names if n in ledger), None)
            if axis is None:
                continue
            values = _tuple_of_strs(node.value)
            if values is None:
                out.append(
                    ctx.violation(
                        self.name,
                        node,
                        f"{axis} must be a literal tuple of strings so the "
                        "canonical-matrix coverage is statically checkable",
                    )
                )
                continue
            entry = ledger[axis]
            if entry["module"] != rel:
                out.append(
                    ctx.violation(
                        self.name,
                        node,
                        f"{axis} is defined in {rel} but the analysis "
                        f"coverage ledger names {entry['module']} — update "
                        "stencil_tpu/analysis/registry.py (and the "
                        "canonical matrix) for the move",
                    )
                )
            missing = [v for v in values if v not in entry["covered"]]
            if missing:
                out.append(
                    ctx.violation(
                        self.name,
                        node,
                        f"{axis} declares {missing} but no canonical "
                        "program covers them — add a program to "
                        "analysis/programs.py and record it in "
                        "analysis/registry.py before shipping the route",
                    )
                )
        return out
