"""Rule ``jax-import``: the modules documented as jax-free must not import
jax at module level.

The telemetry layer's contract (docs/observability.md) is that enabling
metrics/events can never initialize a jax backend — a fresh process that
only touches telemetry must stay backend-free (the fail-closed rank probe
depends on it).  The resilience taxonomy and fault injector are consulted
from exception handlers where jax may be mid-failure, and
``utils/config.py`` is read at import time by everything.  Until this rule,
"never imports jax" was a CHANGES.md claim verified only by a subprocess
test for one module; now any module-level ``import jax`` /
``from jax import ...`` in the declared-jax-free set fails the lint.
Lazy in-function imports remain allowed (that is the sanctioned pattern —
see telemetry/spans.py).
"""

from __future__ import annotations

import ast
from typing import List

from stencil_tpu.lint.framework import FileContext, Rule, register

#: declared-jax-free surface: prefixes and exact files (repo-relative)
JAX_FREE_PREFIXES = ("stencil_tpu/telemetry/", "stencil_tpu/lint/")
JAX_FREE_FILES = {
    "stencil_tpu/resilience/taxonomy.py",
    "stencil_tpu/resilience/inject.py",
    "stencil_tpu/utils/config.py",
    # imported by the jax-free telemetry package (trace dumps) and on
    # exception-handler exit paths — must stay stdlib-only
    "stencil_tpu/utils/artifact.py",
}


def _module_level_imports(tree: ast.Module):
    """Import nodes executed at import time: anything not nested inside a
    function/lambda body (class bodies and module-level if/try blocks all
    execute on import)."""
    in_function = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            for sub in ast.walk(node):
                if sub is not node:
                    in_function.add(id(sub))
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)) and id(node) not in in_function:
            yield node


def _imports_jax(node) -> bool:
    if isinstance(node, ast.Import):
        return any(a.name == "jax" or a.name.startswith("jax.") for a in node.names)
    if isinstance(node, ast.ImportFrom):
        m = node.module or ""
        return node.level == 0 and (m == "jax" or m.startswith("jax."))
    return False


@register
class JaxFreeRule(Rule):
    name = "jax-import"
    why = (
        "telemetry/, resilience/taxonomy|inject, utils/config.py and the "
        "linter itself are contractually jax-free at import time; import "
        "jax lazily inside the function that needs it"
    )

    def applies_to(self, rel: str) -> bool:
        rel = rel.replace("\\", "/")
        return rel in JAX_FREE_FILES or rel.startswith(JAX_FREE_PREFIXES)

    def check(self, ctx: FileContext) -> List:
        out = []
        for node in _module_level_imports(ctx.tree):
            if _imports_jax(node):
                out.append(
                    ctx.violation(
                        self.name,
                        node,
                        "module-level jax import in a declared-jax-free "
                        "module — import jax lazily inside the function "
                        "that needs it (telemetry must never initialize a "
                        "backend; see docs/observability.md)",
                    )
                )
        return out
