"""Small AST helpers shared by the rule modules (stdlib ``ast`` only)."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def dotted(node: ast.expr) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    """Trailing name of the called expression: ``lax.fori_loop(...)`` ->
    ``fori_loop``; ``foo(...)`` -> ``foo``."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def keyword(node: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def const_int_set(node: ast.expr) -> Optional[Set[int]]:
    """``0`` -> {0}; ``(0, 2)`` / ``[0, 2]`` -> {0, 2}; else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out = set()
        for el in node.elts:
            if not (isinstance(el, ast.Constant) and isinstance(el.value, int)):
                return None
            out.add(el.value)
        return out
    return None


def module_defs(tree: ast.Module) -> Dict[str, List[ast.AST]]:
    """Every FunctionDef/AsyncFunctionDef in the module keyed by bare name
    (nested defs included — lint resolution is by-name, best effort)."""
    out: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def called_names(node: ast.AST) -> Set[str]:
    """Bare trailing names of every call in the subtree (``f()``, ``o.f()``
    both yield ``f``) plus bare-Name arguments passed to calls (functions
    handed onward as values, e.g. loop bodies and extender callbacks)."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            cn = call_name(n)
            if cn:
                out.add(cn)
            for a in n.args:
                if isinstance(a, ast.Name):
                    out.add(a.id)
    return out


def is_at_set_call(node: ast.AST) -> bool:
    """``x.at[...].set(...)`` (the jnp indexed-update form)."""
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "set"
        and isinstance(node.func.value, ast.Subscript)
        and isinstance(node.func.value.value, ast.Attribute)
        and node.func.value.value.attr == "at"
    )


def function_scopes(tree: ast.Module) -> Iterator[ast.AST]:
    """The module plus every function node — the scopes rules iterate."""
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, FUNC_NODES):
            yield node


def decorator_names(node) -> List[str]:
    """Dotted names of decorators; for decorator CALLS, the dotted name of
    the called expression (``@partial(jax.jit, ...)`` -> ``partial``)."""
    out = []
    for d in getattr(node, "decorator_list", []):
        target = d.func if isinstance(d, ast.Call) else d
        name = dotted(target)
        if name:
            out.append(name)
    return out
