import sys

from stencil_tpu.lint.cli import main

sys.exit(main())
