"""Command-line front end: ``python -m stencil_tpu.lint`` / ``stencil-lint``.

Exit codes: 0 clean, 1 violations found, 2 usage error.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional, Sequence

from stencil_tpu.lint import framework


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="stencil-lint",
        description=(
            "Machine-check this tree's TPU invariants (validated env reads, "
            "jax-free telemetry, donated-buffer safety, PERF_NOTES layout "
            "traps, tier-1 budget discipline).  See docs/static-analysis.md."
        ),
    )
    p.add_argument(
        "paths",
        nargs="*",
        help="files to lint (default: the whole checked surface — "
        "stencil_tpu/, tests/, bench.py, scripts/*.py)",
    )
    p.add_argument(
        "--select",
        metavar="RULE[,RULE...]",
        help="run only these rules (comma-separated ids)",
    )
    p.add_argument(
        "--changed-only",
        action="store_true",
        help="lint only files changed vs git HEAD (plus untracked) — the "
        "fast pre-commit mode",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable output on stdout"
    )
    p.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalog (id + rationale) and exit",
    )
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for cls in sorted(framework.all_rules(), key=lambda c: c.name):
            print(f"{cls.name}: {cls.why}")
        return 0
    select = args.select.split(",") if args.select else None
    if args.paths and args.changed_only:
        print("--changed-only and explicit paths are exclusive", file=sys.stderr)
        return 2
    try:
        if args.changed_only:
            files = framework.changed_files()
        elif args.paths:
            files = args.paths
        else:
            files = framework.default_files()
        violations = framework.lint_paths(files, select=select)
    except ValueError as e:  # unknown --select rule
        print(str(e), file=sys.stderr)
        return 2
    except OSError as e:  # unreadable/nonexistent path: usage, not lint, error
        print(f"cannot read {e.filename or ''}: {e.strerror}", file=sys.stderr)
        return 2
    if args.json:
        print(framework.render_json(violations, files_checked=len(files)))
    else:
        framework.render_human(violations)
        if not violations:
            print(f"stencil-lint: {len(files)} file(s) clean", file=sys.stderr)
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
