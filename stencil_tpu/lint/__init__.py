"""stencil-lint — AST-based static checks for this tree's TPU invariants.

Entry points:

* ``python -m stencil_tpu.lint`` — lint the default surface, human output.
* ``python -m stencil_tpu.lint --json`` — machine output (CI artifacts).
* ``python -m stencil_tpu.lint --changed-only`` — pre-commit fast path.
* ``from stencil_tpu.lint import run_lint`` — the in-process tier-1 test.

Rule catalog, suppression syntax, and how to add a rule:
``docs/static-analysis.md``.
"""

from stencil_tpu.lint.framework import (  # noqa: F401
    REPO,
    FileContext,
    Rule,
    Suppression,
    Violation,
    all_rules,
    default_files,
    lint_paths,
    lint_source,
    register,
    run_lint,
)
from stencil_tpu.lint.cli import main  # noqa: F401
